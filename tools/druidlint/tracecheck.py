"""tracecheck: shape/dtype/VMEM contract analysis for the engine layer.

An abstract-interpretation pass over `druid_tpu/engine/` that makes the
numeric engine's conventions — `pl.BlockSpec` tile geometry, accumulator
identity dtypes, VMEM residency, AggKernel reduce contracts — mechanically
checked, the way PR 2's druidlint did for the control plane. A kernel edit
that changes a contract now fails the tier-1 lint gate instead of the
on-chip suite.

The contracts live in ONE place: `druid_tpu/engine/contracts.py`, imported
by the engine and loaded (by file path, no package import, no jax) by this
module. Rules here never hard-code a tile constant.

Shape arithmetic like `(R, 128)` and `G2 // 128` is evaluated over an
interval + stride domain (`Sym`): every value carries optional integer
bounds and a known divisor. Module constants resolve through the scanned
module's own assignments and its `contracts` imports (cross-module);
function locals resolve through a forward pass over the function body;
anything unresolvable (results of host planning calls, parameters) falls
back to the bounds `contracts.SYMBOL_BOUNDS` declares — which the engine
enforces at runtime, so the static and dynamic contracts cannot drift.

Rules (all plug into the registry/baseline/suppression/--fail-on-new
machinery from PR 2):
  pallas-tile-shape       block shapes statically resolvable, lane-aligned,
                          index_map arity/rank consistent, out_spec shape
                          textually identical to the out_shape declaration
  pallas-accum-dtype      reduce identity literals carry their contracted
                          dtype; no 64-bit dtype inside a kernel body
  vmem-budget             worst-case sum of declared tile bytes under the
                          configured VMEM cap
  x64-dtype               jnp.int64/float64 in traced device code without
                          an x64 gate (silent truncation under default JAX)
  agg-contract            AggKernel subclasses define the required methods,
                          fold-kind kernels define device_combine,
                          signature() expressions are distinct
  preferred-element-type  device matmuls always pin their accumulator dtype
"""
from __future__ import annotations

import ast
import importlib.util
import math
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.druidlint.core import Finding, ModuleContext, rule
from tools.druidlint.rules import (_FUNC_DEFS, _collect_traced_functions,
                                   _terminal)

# ---- contracts loading ----------------------------------------------------

_CONTRACTS_REL = "druid_tpu/engine/contracts.py"
_CONTRACTS_CACHE: Dict[str, Tuple[float, Dict[str, object]]] = {}


def contracts_path(root: str = ".") -> Optional[Path]:
    """The contracts file a scan of `root` validates against: the root's
    own engine tree when present, else the contracts shipped beside this
    linter (synthetic-violation fixtures have no engine tree). The cache
    signer hashes the same file, so contract edits always invalidate."""
    path = Path(root) / _CONTRACTS_REL
    if not path.is_file():
        path = Path(__file__).resolve().parents[2] / _CONTRACTS_REL
    return path if path.is_file() else None


def load_contracts(root: str = ".") -> Dict[str, object]:
    """Load the engine contract table by file path (no package import — the
    engine package enables x64 and pulls jax on import, which the linter
    must not)."""
    path = contracts_path(root)
    if path is None:
        return {}
    key = str(path.resolve())
    mtime = path.stat().st_mtime_ns
    cached = _CONTRACTS_CACHE.get(key)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    spec = importlib.util.spec_from_file_location("_druidlint_contracts", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    table = {k: v for k, v in vars(mod).items() if not k.startswith("_")}
    _CONTRACTS_CACHE[key] = (mtime, table)
    return table


def _contracts(ctx: ModuleContext) -> Dict[str, object]:
    return load_contracts(getattr(ctx.config, "root", "."))


# ---- the Sym interval + stride domain -------------------------------------

class Sym:
    """An integer abstract value: optional [lo, hi] bounds plus a known
    divisor (`value ≡ 0 (mod mult)`). Exact values have lo == hi."""

    __slots__ = ("lo", "hi", "mult")

    def __init__(self, lo: Optional[int], hi: Optional[int], mult: int = 1):
        self.lo, self.hi = lo, hi
        self.mult = max(1, mult)

    @classmethod
    def exact(cls, v: int) -> "Sym":
        return cls(v, v, abs(v) if v else 1)

    @property
    def value(self) -> Optional[int]:
        return self.lo if self.lo is not None and self.lo == self.hi else None

    def bounded(self) -> bool:
        return self.lo is not None and self.hi is not None

    def multiple_of(self, m: int) -> bool:
        if self.value is not None:
            return self.value % m == 0
        return self.mult % m == 0

    def __repr__(self):  # pragma: no cover - debug aid
        return f"Sym[{self.lo},{self.hi}]%{self.mult}"


def _gcd(a: int, b: int) -> int:
    return math.gcd(a, b)


def _sym_add(a: Sym, b: Sym) -> Sym:
    lo = None if a.lo is None or b.lo is None else a.lo + b.lo
    hi = None if a.hi is None or b.hi is None else a.hi + b.hi
    return Sym(lo, hi, _gcd(a.mult, b.mult))


def _sym_sub(a: Sym, b: Sym) -> Sym:
    lo = None if a.lo is None or b.hi is None else a.lo - b.hi
    hi = None if a.hi is None or b.lo is None else a.hi - b.lo
    return Sym(lo, hi, _gcd(a.mult, b.mult))


def _sym_mul(a: Sym, b: Sym) -> Sym:
    if a.bounded() and b.bounded():
        prods = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
        return Sym(min(prods), max(prods), a.mult * b.mult)
    return Sym(None, None, a.mult * b.mult)


def _sym_floordiv(a: Sym, b: Sym) -> Optional[Sym]:
    d = b.value
    if d is None or d <= 0:
        return None
    lo = None if a.lo is None else a.lo // d
    hi = None if a.hi is None else a.hi // d
    mult = a.mult // d if a.mult % d == 0 else 1
    return Sym(lo, hi, mult)


def _sym_mod(a: Sym, b: Sym) -> Optional[Sym]:
    d = b.value
    if d is None or d <= 0:
        return None
    if a.value is not None:
        return Sym.exact(a.value % d)
    return Sym(0, d - 1, 1)


def _sym_pow(a: Sym, b: Sym) -> Optional[Sym]:
    if a.value is not None and b.value is not None and b.value >= 0:
        return Sym.exact(a.value ** b.value)
    return None


def _sym_minmax(args: List[Sym], is_max: bool) -> Sym:
    pick = max if is_max else min
    los = [a.lo for a in args]
    his = [a.hi for a in args]
    if is_max:
        # lo of max: the largest known lo; hi of max: needs every hi
        lo = pick([l for l in los if l is not None], default=None)
        hi = None if any(h is None for h in his) else pick(his)
    else:
        lo = None if any(l is None for l in los) else pick(los)
        hi = pick([h for h in his if h is not None], default=None)
    # the result can be ANY argument, so the stride must divide all of them
    mult = args[0].mult
    for a in args[1:]:
        mult = _gcd(mult, a.mult)
    return Sym(lo, hi, mult)


def _round_up_int(x: int, m: int) -> int:
    return -(-x // m) * m


class SymEval:
    """Evaluate an AST expression to a Sym (or a tuple of results for
    ast.Tuple), given an environment of named Syms and the contract table."""

    def __init__(self, env: Dict[str, Sym], contracts: Dict[str, object]):
        self.env = env
        self.contracts = contracts
        self.bounds = contracts.get("SYMBOL_BOUNDS", {}) or {}

    def eval(self, node: ast.AST):
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return Sym.exact(node.value)
        if isinstance(node, ast.Name):
            s = self.env.get(node.id)
            if s is not None:
                return s
            v = self.contracts.get(node.id)   # bare contract-constant name
            if isinstance(v, int) and not isinstance(v, bool):
                return Sym.exact(v)
            # declared symbol bound: names the function env cannot see
            # (comprehension targets, opaque planning results) resolve
            # through SYMBOL_BOUNDS exactly like parameters do
            b = self.bounds.get(node.id)
            if b:
                return Sym(b[0], b[1], b[2])
            return None
        if isinstance(node, ast.Attribute):
            # contracts.X / any <alias>.X whose terminal names a contract int
            v = self.contracts.get(node.attr)
            if isinstance(v, int) and not isinstance(v, bool):
                return Sym.exact(v)
            return None
        if isinstance(node, ast.Tuple):
            return tuple(self.eval(e) for e in node.elts)
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            s = self.eval(node.operand)
            if isinstance(s, Sym):
                return _sym_sub(Sym.exact(0), s)
            return None
        if isinstance(node, ast.BinOp):
            a, b = self.eval(node.left), self.eval(node.right)
            if not (isinstance(a, Sym) and isinstance(b, Sym)):
                return None
            if isinstance(node.op, ast.Add):
                return _sym_add(a, b)
            if isinstance(node.op, ast.Sub):
                return _sym_sub(a, b)
            if isinstance(node.op, ast.Mult):
                return _sym_mul(a, b)
            if isinstance(node.op, ast.FloorDiv):
                return _sym_floordiv(a, b)
            if isinstance(node.op, ast.Mod):
                return _sym_mod(a, b)
            if isinstance(node.op, ast.Pow):
                return _sym_pow(a, b)
            return None
        if isinstance(node, ast.Call):
            name = _terminal(node.func)
            if name == "len" and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Name):
                b = self.bounds.get(f"len({node.args[0].id})")
                if b:
                    return Sym(b[0], b[1], b[2])
                return None
            if name in ("max", "min"):
                args = [self.eval(a) for a in node.args]
                if args and all(isinstance(a, Sym) for a in args):
                    return _sym_minmax(args, name == "max")
                return None
            if name in ("_round_up", "round_up") and len(node.args) == 2:
                x, m = self.eval(node.args[0]), self.eval(node.args[1])
                if isinstance(x, Sym) and isinstance(m, Sym) \
                        and m.value and m.value > 0:
                    lo = None if x.lo is None \
                        else _round_up_int(max(x.lo, 0), m.value)
                    hi = None if x.hi is None \
                        else _round_up_int(x.hi, m.value)
                    return Sym(lo, hi, m.value)
                return None
            return None
        return None


def _module_env(ctx: ModuleContext,
                contracts: Dict[str, object]) -> Dict[str, Sym]:
    """Top-level constants: `from ...contracts import X` names resolve
    cross-module against the loaded contract table; plain `NAME = <expr>`
    assignments evaluate in source order."""
    env: Dict[str, Sym] = {}
    ev = SymEval(env, contracts)
    for node in ctx.tree.body:
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.rsplit(".", 1)[-1] == "contracts":
            for alias in node.names:
                v = contracts.get(alias.name)
                if isinstance(v, int) and not isinstance(v, bool):
                    env[alias.asname or alias.name] = Sym.exact(v)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            s = ev.eval(node.value)
            if isinstance(s, Sym):
                env[node.targets[0].id] = s
    return env


def _function_env(ctx: ModuleContext, fn: Optional[ast.AST],
                  contracts: Dict[str, object],
                  module_env: Dict[str, Sym]) -> Dict[str, Sym]:
    """Forward pass over a function body: parameters and unresolvable
    assignments (host planning calls, array attributes) fall back to the
    declared SYMBOL_BOUNDS; everything else evaluates symbolically."""
    env = dict(module_env)
    bounds = contracts.get("SYMBOL_BOUNDS", {}) or {}

    def bound_sym(name: str) -> Optional[Sym]:
        b = bounds.get(name)
        return Sym(b[0], b[1], b[2]) if b else None

    if fn is None:
        return env
    for a in list(getattr(fn.args, "args", [])) + \
            list(getattr(fn.args, "kwonlyargs", [])):
        s = bound_sym(a.arg)
        if s is not None:
            env[a.arg] = s
    ev = SymEval(env, contracts)
    assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
    for node in sorted(assigns, key=lambda n: (n.lineno, n.col_offset)):
        if len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if isinstance(tgt, ast.Name):
            s = ev.eval(node.value)
            if not isinstance(s, Sym):
                s = bound_sym(tgt.id)
            if isinstance(s, Sym):
                env[tgt.id] = s
        elif isinstance(tgt, ast.Tuple) \
                and all(isinstance(e, ast.Name) for e in tgt.elts):
            val = ev.eval(node.value)
            if isinstance(val, tuple) and len(val) == len(tgt.elts) \
                    and all(isinstance(v, Sym) for v in val):
                for e, v in zip(tgt.elts, val):
                    env[e.id] = v
            else:
                for e in tgt.elts:
                    s = bound_sym(e.id)
                    if s is not None:
                        env[e.id] = s
    return env


# ---- shared AST helpers ---------------------------------------------------

def _dump(node: ast.AST) -> str:
    return ast.dump(node, annotate_fields=False)


def _call_kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _block_shape(call: ast.Call) -> Optional[ast.AST]:
    if call.args:
        return call.args[0]
    return _call_kw(call, "block_shape")


def _index_map(call: ast.Call) -> Optional[ast.AST]:
    if len(call.args) > 1:
        return call.args[1]
    return _call_kw(call, "index_map")


def _spec_entries(node: ast.AST) -> List[Tuple[ast.Call, Optional[ast.AST]]]:
    """Flatten an in_specs/out_specs expression to (BlockSpec call,
    multiplicity expr or None) pairs. Handles `[spec, ...]`,
    `[spec] * expr`, list concatenation (`A + B`), a comprehension over a
    named iterable (multiplicity = a synthesized `len(<name>)`, resolved
    via SYMBOL_BOUNDS), and a bare spec."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _spec_entries(node.left) + _spec_entries(node.right)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        lst, mult = node.left, node.right
        if not isinstance(lst, (ast.List, ast.Tuple)):
            lst, mult = node.right, node.left
        if isinstance(lst, (ast.List, ast.Tuple)):
            return [(c, mult) for c, _ in _spec_entries(lst)]
        return []
    if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
        entries = _spec_entries(node.elt)
        if not entries:
            return []
        mult: ast.AST
        if len(node.generators) == 1 \
                and isinstance(node.generators[0].iter, ast.Name) \
                and not node.generators[0].ifs:
            # multiplicity = len(<iterable>) — SymEval resolves it through
            # the declared SYMBOL_BOUNDS ("len(packed_rws)" style keys)
            mult = ast.copy_location(
                ast.Call(func=ast.Name(id="len", ctx=ast.Load()),
                         args=[node.generators[0].iter], keywords=[]),
                node)
        else:
            # filtered / nested / opaque iteration: force the vmem rule's
            # "multiplicity not statically bounded" finding rather than
            # silently under-counting
            mult = ast.copy_location(
                ast.Name(id="__unbounded_spec_multiplicity__",
                         ctx=ast.Load()), node)
        return [(c, mult) for c, _ in entries]
    if isinstance(node, (ast.List, ast.Tuple)):
        out = []
        for el in node.elts:
            out.extend(_spec_entries(el))
        return out
    if isinstance(node, ast.Call) and _terminal(node.func) == "BlockSpec":
        return [(node, None)]
    return []


def _enclosing_grid(ctx: ModuleContext, node: ast.AST) -> Optional[ast.AST]:
    """The `grid=` tuple of the GridSpec/pallas_call the node sits inside."""
    cur = ctx.parent(node)
    while cur is not None:
        if isinstance(cur, ast.Call) \
                and _terminal(cur.func) in ("GridSpec", "pallas_call"):
            g = _call_kw(cur, "grid")
            if g is not None:
                return g
        cur = ctx.parent(cur)
    return None


def _kernel_functions(ctx: ModuleContext) -> List[ast.AST]:
    """Function defs passed by name as the first argument to pallas_call —
    their bodies run on-chip under Mosaic's lowering rules."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNC_DEFS):
            defs_by_name.setdefault(node.name, []).append(node)
    out: List[ast.AST] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and _terminal(node.func) == "pallas_call" and node.args \
                and isinstance(node.args[0], ast.Name):
            out.extend(defs_by_name.get(node.args[0].id, []))
    return out


# ---- pallas-tile-shape ----------------------------------------------------

@rule("pallas-tile-shape", "error",
      "pl.BlockSpec tile geometry violates the engine contract")
def check_pallas_tile_shape(ctx: ModuleContext) -> Iterable[Finding]:
    """Every `pl.BlockSpec` in the pallas modules (config `pallas-modules`)
    must declare a block shape the abstract interpreter can bound, with a
    last dim that is a multiple of contracts.LANE (Mosaic tiles are
    (sublane, 128); an unaligned last dim fails on-chip, not at trace
    time). The index_map lambda's arity must match the grid rank, its
    returned tuple the block rank, and out_specs' shapes must stay
    textually identical to the out_shape ShapeDtypeStruct declaration."""
    if not ctx.path_matches(ctx.config.pallas_modules):
        return
    contracts = _contracts(ctx)
    lane = contracts.get("LANE", 128)
    module_env = _module_env(ctx, contracts)
    fn_envs: Dict[Optional[ast.AST], Dict[str, Sym]] = {}

    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _terminal(node.func) == "BlockSpec"):
            continue
        shape = _block_shape(node)
        if shape is None:
            continue                      # memory_space-only spec: whole ref
        if not isinstance(shape, ast.Tuple):
            yield ctx.finding(node, "BlockSpec block shape is not a static "
                                    "tuple — the tile geometry must be "
                                    "resolvable without running the engine")
            continue
        fn = ctx.enclosing_function(node)
        if fn not in fn_envs:
            fn_envs[fn] = _function_env(ctx, fn, contracts, module_env)
        ev = SymEval(fn_envs[fn], contracts)
        dims = [ev.eval(e) for e in shape.elts]
        bad = [i for i, s in enumerate(dims)
               if not (isinstance(s, Sym) and s.bounded())]
        if bad:
            yield ctx.finding(
                shape, f"block shape dim(s) {bad} not statically resolvable "
                       f"— declare the bound in contracts.SYMBOL_BOUNDS or "
                       f"use contract constants")
        elif dims and not dims[-1].multiple_of(lane):
            yield ctx.finding(
                shape, f"block shape last dim is not a multiple of the "
                       f"{lane}-lane tile width (Mosaic lowers (sublane, "
                       f"{lane}) tiles; this fails on-chip only)")
        imap = _index_map(node)
        if isinstance(imap, ast.Lambda):
            grid = _enclosing_grid(ctx, node)
            if isinstance(grid, ast.Tuple):
                nargs = len(imap.args.args)
                if nargs != len(grid.elts):
                    yield ctx.finding(
                        imap, f"index_map takes {nargs} arg(s) but the grid "
                              f"has rank {len(grid.elts)}")
            if isinstance(imap.body, ast.Tuple) \
                    and len(imap.body.elts) != len(shape.elts):
                yield ctx.finding(
                    imap, f"index_map returns {len(imap.body.elts)} "
                          f"coordinate(s) for a rank-{len(shape.elts)} "
                          f"block shape")

    # out_specs shape ↔ out_shape ShapeDtypeStruct shape: the kernel writes
    # orefs[j][:, :] assuming they agree; a drift reshapes the accumulator
    # grid silently. The contract is textual identity of the shape exprs.
    for fn in [n for n in ast.walk(ctx.tree) if isinstance(n, _FUNC_DEFS)]:
        out_spec_shapes: Set[str] = set()
        out_shape_shapes: Set[str] = set()
        anchor = None
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _terminal(node.func) in ("GridSpec", "pallas_call"):
                specs = _call_kw(node, "out_specs")
                if specs is not None:
                    for call, _ in _spec_entries(specs):
                        sh = _block_shape(call)
                        if sh is not None:
                            out_spec_shapes.add(_dump(sh))
                            anchor = anchor or call
            elif _terminal(node.func) == "ShapeDtypeStruct" and node.args:
                out_shape_shapes.add(_dump(node.args[0]))
        if len(out_spec_shapes) == 1 and len(out_shape_shapes) == 1 \
                and out_spec_shapes != out_shape_shapes:
            yield ctx.finding(
                anchor, "out_specs block shape differs from the out_shape "
                        "ShapeDtypeStruct shape — the full-grid accumulator "
                        "contract requires them textually identical")


# ---- pallas-accum-dtype ---------------------------------------------------

_INF_NAMES = {"inf", "infty", "Inf", "Infinity"}


def _literal_value(node: ast.AST):
    """Evaluate a pure-literal arithmetic expression (ints, floats, ±inf
    spelled jnp.inf / np.inf / math.inf / float('inf'))."""
    if isinstance(node, ast.Constant) \
            and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Attribute) and node.attr in _INF_NAMES:
        return float("inf")
    if isinstance(node, ast.Name) and node.id in _INF_NAMES:
        return float("inf")
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _literal_value(node.operand)
        return None if v is None else -v
    if isinstance(node, ast.BinOp):
        a, b = _literal_value(node.left), _literal_value(node.right)
        if a is None or b is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.Pow):
                return a ** b
            if isinstance(node.op, ast.FloorDiv):
                return a // b
        except (ZeroDivisionError, OverflowError, ValueError):
            return None
        return None
    if isinstance(node, ast.Call) and _terminal(node.func) == "float" \
            and len(node.args) == 1 \
            and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        try:
            return float(node.args[0].value)
        except ValueError:
            return None
    return None


@rule("pallas-accum-dtype", "error",
      "accumulator identity literal carries the wrong dtype, or a 64-bit "
      "dtype appears inside a kernel body")
def check_pallas_accum_dtype(ctx: ModuleContext) -> Iterable[Finding]:
    """In pallas modules, every dtype constructor applied to a reduce
    identity literal must use the dtype contracts.REDUCE_IDENTITIES maps it
    to — `jnp.int32(2**31 - 1)` for the int-min identity, `jnp.float32(inf)`
    for the float-min identity, and so on; a drifted identity dtype poisons
    the whole accumulator grid. 64-bit dtypes are banned inside kernel
    bodies outright (Mosaic cannot lower them on these chips): the
    `astype(jnp.int64)` widenings belong outside the kernel."""
    if not ctx.path_matches(ctx.config.pallas_modules):
        return
    contracts = _contracts(ctx)
    identities = contracts.get("REDUCE_IDENTITIES", {}) or {}
    dtype_names = set(contracts.get("DTYPE_BYTES", {}) or ())
    x64 = set(contracts.get("X64_DTYPES", ()) or ())

    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in dtype_names
                and len(node.args) == 1 and not node.keywords):
            continue
        v = _literal_value(node.args[0])
        if v is None or v not in identities:
            continue
        want = identities[v]
        if node.func.attr != want:
            yield ctx.finding(
                node, f"reduce identity {ast.unparse(node.args[0])} must be "
                      f"constructed as {want} (got {node.func.attr}) — a "
                      f"mismatched identity dtype corrupts every group's "
                      f"accumulator")

    seen: Set[Tuple[int, int]] = set()
    for fn in _kernel_functions(ctx):
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and node.attr in x64:
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield ctx.finding(
                    node, f"{node.attr} inside the kernel body of "
                          f"{getattr(fn, 'name', '<kernel>')}() — Mosaic "
                          f"cannot lower 64-bit element types; widen "
                          f"outside the kernel (lo/hi limbs inside)")

    # BENCH_r04 regression class: a BlockSpec index_map returning a BARE
    # Python int promotes to i64 under the repo-global x64 flag, and Mosaic
    # fails to legalize the lowered index map's mixed `func.return
    # (i32, i64)` — an on-TPU-only compile failure the CPU interpreter
    # never sees. Constants in index maps must be built typed inside the
    # lambda (jnp.int32(0)).
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _terminal(node.func) == "BlockSpec"):
            continue
        imap = _index_map(node)
        if not isinstance(imap, ast.Lambda):
            continue
        rets = imap.body.elts if isinstance(imap.body, ast.Tuple) \
            else [imap.body]
        for r in rets:
            if isinstance(r, ast.Constant) and isinstance(r.value, int) \
                    and not isinstance(r.value, bool):
                yield ctx.finding(
                    r, f"untyped int constant {r.value} in a BlockSpec "
                       f"index_map — promotes to i64 under x64 and Mosaic "
                       f"fails to legalize the (i32, i64) func.return "
                       f"(the BENCH_r04 on-TPU break); build it typed "
                       f"inside the lambda: jnp.int32({r.value})")


# ---- vmem-budget ----------------------------------------------------------

@rule("vmem-budget", "error",
      "declared pallas tiles exceed the VMEM budget")
def check_vmem_budget(ctx: ModuleContext) -> Iterable[Finding]:
    """The worst-case sum of BlockSpec tile bytes (upper bounds of the
    symbolic shapes × spec multiplicity × the widest kernel element type)
    must stay under the configured cap (`[tool.druidlint] vmem-cap-bytes`,
    default contracts.VMEM_BUDGET_BYTES): the kernel keeps every declared
    tile resident, so a shape/cap drift that compiles fine on the
    interpreter OOMs VMEM on-chip."""
    if not ctx.path_matches(ctx.config.pallas_modules):
        return
    contracts = _contracts(ctx)
    cap = int(getattr(ctx.config, "vmem_cap_bytes", 0) or 0) \
        or contracts.get("VMEM_BUDGET_BYTES", 12 * 1024 * 1024)
    elem_bytes = contracts.get("PALLAS_MAX_TILE_DTYPE_BYTES", 4)
    module_env = _module_env(ctx, contracts)

    for call in ast.walk(ctx.tree):
        if not (isinstance(call, ast.Call)
                and _terminal(call.func) in ("GridSpec", "pallas_call")):
            continue
        entries: List[Tuple[ast.Call, Optional[ast.AST]]] = []
        for kw_name in ("in_specs", "out_specs"):
            specs = _call_kw(call, kw_name)
            if specs is not None:
                entries.extend(_spec_entries(specs))
        if not entries:
            continue
        fn = ctx.enclosing_function(call)
        ev = SymEval(_function_env(ctx, fn, contracts, module_env),
                     contracts)
        total = 0
        for spec_call, mult_expr in entries:
            shape = _block_shape(spec_call)
            if not isinstance(shape, ast.Tuple):
                continue
            dims = [ev.eval(e) for e in shape.elts]
            if not all(isinstance(s, Sym) and s.bounded() for s in dims):
                continue                # pallas-tile-shape reports these
            cells = 1
            for s in dims:
                cells *= max(s.hi, 0)
            mult = 1
            if mult_expr is not None:
                m = ev.eval(mult_expr)
                if not (isinstance(m, Sym) and m.hi is not None):
                    yield ctx.finding(
                        mult_expr, "spec-list multiplicity not statically "
                                   "bounded — the VMEM budget cannot be "
                                   "checked; bound it via "
                                   "contracts.SYMBOL_BOUNDS")
                    mult = 0
                else:
                    mult = max(m.hi, 0)
            total += cells * mult * elem_bytes
        if total > cap:
            yield ctx.finding(
                call, f"declared tiles need up to {total} bytes of VMEM, "
                      f"over the {cap}-byte budget — shrink the window/"
                      f"group caps in contracts.py or raise vmem-cap-bytes "
                      f"deliberately")


# ---- x64-dtype ------------------------------------------------------------

_X64_GATES = {"x64_enabled", "jax_enable_x64"}
_X64_MODULES = {"jnp", "jax", "np", "numpy", "onp"}


@rule("x64-dtype", "error",
      "64-bit dtype in traced device code without an x64 gate")
def check_x64_dtype(ctx: ModuleContext) -> Iterable[Finding]:
    """Inside traced device code (config `device-modules`; kernel bodies
    passed to pallas_call count), `jnp.int64` / `jnp.float64` silently
    produce 32-bit arrays when JAX's x64 flag is off — a truncation that
    corrupts long sums near 2**31 without any error. Either gate the dtype
    choice on `jax.config.jax_enable_x64` (reading the flag anywhere in the
    function counts as the gate) or suppress with a rationale where the
    engine's global x64 enablement makes the wide dtype load-bearing."""
    if not ctx.path_matches(ctx.config.device_modules):
        return
    contracts = _contracts(ctx)
    x64 = set(contracts.get("X64_DTYPES", ("int64", "uint64", "float64")))
    traced = _collect_traced_functions(ctx, frozenset({"pallas_call"}))
    seen: Set[Tuple[int, int]] = set()
    for fn in traced:
        gated = any(
            (isinstance(n, ast.Attribute) and n.attr in _X64_GATES)
            or (isinstance(n, ast.Name) and n.id in _X64_GATES)
            for n in ast.walk(fn))
        if gated:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and node.attr in x64 \
                    and _terminal(node.value) in _X64_MODULES:
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield ctx.finding(
                    node, f"{_terminal(node.value)}.{node.attr} in traced "
                          f"function {getattr(fn, 'name', '<fn>')}() — "
                          f"silently 32-bit when x64 is off; gate on "
                          f"jax.config.jax_enable_x64 or widen on host")


# ---- agg-contract ---------------------------------------------------------

@rule("agg-contract", "error",
      "AggKernel subclass violates the reduce contract")
def check_agg_contract(ctx: ModuleContext) -> Iterable[Finding]:
    """In kernel modules (config `kernel-modules`), every AggKernel
    subclass must define the contracts.AGG_REQUIRED_METHODS
    (signature/update/combine/empty_state); classes whose effective
    reduce_kind is "fold" (the base default — unless the class or an
    in-module ancestor overrides it, or __init__ assigns it dynamically)
    must define device_combine, because the sharded merge folds states
    pairwise on device. signature() return expressions must be distinct
    across kernels in a module: the jit caches key on them, and two kernels
    sharing a signature silently share compiled programs."""
    if not ctx.path_matches(ctx.config.kernel_modules):
        return
    contracts = _contracts(ctx)
    required = contracts.get(
        "AGG_REQUIRED_METHODS",
        ("signature", "update", "combine", "empty_state"))
    fold_required = contracts.get("AGG_FOLD_REQUIRED", ("device_combine",))

    classes: Dict[str, ast.ClassDef] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            classes[node.name] = node

    def chain(cls: ast.ClassDef) -> List[ast.ClassDef]:
        """cls plus in-module ancestors, base-class AggKernel excluded."""
        out, todo, seen = [], [cls.name], set()
        while todo:
            name = todo.pop()
            if name in seen or name == "AggKernel":
                continue
            seen.add(name)
            c = classes.get(name)
            if c is None:
                continue
            out.append(c)
            todo.extend(_terminal(b) for b in c.bases)
        return out

    def derives_agg(cls: ast.ClassDef) -> bool:
        todo = [_terminal(b) for b in cls.bases]
        seen = set()
        while todo:
            name = todo.pop()
            if name == "AggKernel":
                return True
            if name in seen:
                continue
            seen.add(name)
            c = classes.get(name)
            if c is not None:
                todo.extend(_terminal(b) for b in c.bases)
        return False

    sig_exprs: Dict[str, List[Tuple[str, ast.AST]]] = {}
    for cls in classes.values():
        if cls.name == "AggKernel" or not derives_agg(cls):
            continue
        ch = chain(cls)
        methods: Dict[str, ast.AST] = {}
        class_rk: Optional[str] = None
        init_assigns_rk = False
        for c in ch:                     # cls first: nearest wins
            for item in c.body:
                if isinstance(item, _FUNC_DEFS):
                    methods.setdefault(item.name, item)
                elif isinstance(item, ast.Assign):
                    for t in item.targets:
                        if isinstance(t, ast.Name) \
                                and t.id == "reduce_kind" \
                                and class_rk is None \
                                and isinstance(item.value, ast.Constant):
                            class_rk = item.value.value
        init = methods.get("__init__")
        if init is not None:
            for node in ast.walk(init):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and t.attr == "reduce_kind":
                            init_assigns_rk = True
        missing = [m for m in required if m not in methods]
        if missing:
            yield ctx.finding(
                cls, f"AggKernel subclass {cls.name} missing required "
                     f"method(s): {', '.join(missing)}")
        if not init_assigns_rk and (class_rk or "fold") == "fold":
            fold_missing = [m for m in fold_required if m not in methods]
            if fold_missing:
                yield ctx.finding(
                    cls, f"{cls.name} has reduce_kind \"fold\" (the base "
                         f"default) but defines no "
                         f"{', '.join(fold_missing)} — the sharded merge "
                         f"all_gathers and folds states pairwise on device")
        sig = methods.get("signature")
        if sig is not None and sig in cls.body:   # defined here, not inherited
            rets = [n.value for n in ast.walk(sig)
                    if isinstance(n, ast.Return) and n.value is not None]
            if rets:
                key = "|".join(_dump(r) for r in rets)
                sig_exprs.setdefault(key, []).append((cls.name, sig))
    for key, owners in sig_exprs.items():
        if len(owners) > 1:
            names = ", ".join(n for n, _ in owners)
            for _, sig in owners[1:]:
                yield ctx.finding(
                    sig, f"signature() return expression duplicated across "
                         f"kernels ({names}) — the jit caches key on it, "
                         f"so these kernels would share compiled programs")


# ---- preferred-element-type -----------------------------------------------

_MATMUL_CALLS = {"dot_general", "dot", "matmul", "einsum", "tensordot"}
_DEVICE_NS = {"lax", "jnp"}


@rule("preferred-element-type", "error",
      "device matmul without preferred_element_type")
def check_preferred_element_type(ctx: ModuleContext) -> Iterable[Finding]:
    """`lax.dot_general` / `jnp.matmul`-family calls in device modules must
    pass `preferred_element_type`: without it the MXU accumulates int8
    products in int8 (wrapping) and bf16 products in bf16 (losing the exact
    f32 accumulation the mm path's error analysis assumes)."""
    if not ctx.path_matches(ctx.config.device_modules):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MATMUL_CALLS
                and _terminal(node.func.value) in _DEVICE_NS):
            continue
        if not any(kw.arg == "preferred_element_type"
                   for kw in node.keywords):
            yield ctx.finding(
                node, f"{_terminal(node.func.value)}.{node.func.attr}() "
                      f"without preferred_element_type — the MXU "
                      f"accumulator dtype must be pinned (int32 for int8 "
                      f"rows, float32 for bf16 rows)")


# ---- shard-spec -----------------------------------------------------------

def _partition_spec_names(ctx: ModuleContext) -> Set[str]:
    """Local names PartitionSpec is importable under (incl. aliases)."""
    names = {"PartitionSpec"}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
    return names


def _mesh_axis_sources(ctx: ModuleContext) -> Tuple[Set[str], Set[str]]:
    """(variable names bound from <mesh>.axis_names[...], literal axis
    strings declared by Mesh(...) constructions) — the two ways a module
    can legitimately name a mesh axis."""
    axis_vars: Set[str] = set()
    axis_literals: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Subscript) \
                and isinstance(node.value.value, ast.Attribute) \
                and node.value.value.attr == "axis_names":
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    axis_vars.add(tgt.id)
        if isinstance(node, ast.Call) and _terminal(node.func) == "Mesh":
            cands = list(node.args[1:2]) + [
                kw.value for kw in node.keywords if kw.arg == "axis_names"]
            for cand in cands:
                if isinstance(cand, (ast.Tuple, ast.List)):
                    for e in cand.elts:
                        if isinstance(e, ast.Constant) and isinstance(e.value, str):
                            axis_literals.add(e.value)
    return axis_vars, axis_literals


def _own_returns(fn: ast.AST) -> List[ast.Return]:
    """Return statements belonging to `fn` itself (nested defs/lambdas have
    their own returns and must not count)."""
    out: List[ast.Return] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_DEFS + (ast.Lambda,)):
                continue
            if isinstance(child, ast.Return):
                out.append(child)
            walk(child)

    walk(fn)
    return out


@rule("shard-spec", "error",
      "shard_map partition specs disagree with the mesh or body")
def check_shard_spec(ctx: ModuleContext) -> Iterable[Finding]:
    """In sharding modules (config `shard-modules`), every `shard_map`
    call's partition specs must agree with its body and its mesh:
    `in_specs` tuples need one entry per body positional parameter,
    `out_specs` tuples one entry per element of the body's returned tuple,
    and every PartitionSpec axis argument must be derived from the mesh —
    a name bound from mesh.axis_names[...] or a literal axis a Mesh(...)
    construction in the module declares. A resharding edit that breaks any
    of these otherwise surfaces in the multichip suite (or as a silent
    replication of what should be sharded), not at lint time."""
    if not ctx.path_matches(ctx.config.shard_modules):
        return
    p_names = _partition_spec_names(ctx)
    axis_vars, axis_literals = _mesh_axis_sources(ctx)

    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNC_DEFS):
            defs_by_name.setdefault(node.name, []).append(node)

    def spec_entries(node) -> Optional[List[ast.AST]]:
        return list(node.elts) if isinstance(node, (ast.Tuple, ast.List)) \
            else None

    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _terminal(node.func) == "shard_map"):
            continue
        body = None
        if node.args and isinstance(node.args[0], ast.Name):
            cands = defs_by_name.get(node.args[0].id, [])
            body = cands[0] if len(cands) == 1 else None
        in_specs = _call_kw(node, "in_specs")
        out_specs = _call_kw(node, "out_specs")

        # arity: in_specs entries ↔ body positional parameters (defaulted
        # params are optional, so any count in [required, total] is valid)
        if body is not None and in_specs is not None:
            entries = spec_entries(in_specs)
            n_pos = len(getattr(body.args, "posonlyargs", [])) \
                + len(body.args.args)
            n_required = n_pos - len(body.args.defaults)
            if entries is not None and body.args.vararg is None \
                    and not (n_required <= len(entries) <= n_pos):
                yield ctx.finding(
                    in_specs, f"in_specs has {len(entries)} spec(s) but "
                              f"body {body.name}() takes {n_required}"
                              f"{f'-{n_pos}' if n_pos != n_required else ''} "
                              f"positional parameter(s)")

        # arity: out_specs entries ↔ body return tuple
        if body is not None and out_specs is not None:
            entries = spec_entries(out_specs)
            if entries is not None:
                ret_lens = set()
                resolvable = True
                for ret in _own_returns(body):
                    if isinstance(ret.value, ast.Tuple):
                        ret_lens.add(len(ret.value.elts))
                    else:
                        resolvable = False
                if resolvable and len(ret_lens) == 1 \
                        and ret_lens != {len(entries)}:
                    yield ctx.finding(
                        out_specs, f"out_specs has {len(entries)} spec(s) "
                                   f"but body {body.name}() returns a "
                                   f"{ret_lens.pop()}-tuple")

        # axis provenance: every PartitionSpec argument must trace to the
        # mesh. Skip when the module declares no axis source at all (a
        # fixture or a mesh passed opaquely) — no false positives.
        if not axis_vars and not axis_literals:
            continue
        for spec_src in (in_specs, out_specs):
            if spec_src is None:
                continue
            for sub in ast.walk(spec_src):
                if not (isinstance(sub, ast.Call)
                        and _terminal(sub.func) in p_names):
                    continue
                for arg in sub.args:
                    if isinstance(arg, ast.Constant) and arg.value is None:
                        continue
                    if isinstance(arg, ast.Name) and arg.id in axis_vars:
                        continue
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str) \
                            and arg.value in axis_literals:
                        continue
                    yield ctx.finding(
                        arg, f"PartitionSpec axis {ast.dump(arg) if not isinstance(arg, ast.Constant) else arg.value!r} "
                             f"is not derived from the mesh (bind it from "
                             f"mesh.axis_names[...] or declare it in the "
                             f"Mesh construction)")


_SPEC_CTORS = ("PartitionSpec", "NamedSharding")


def _sharding_ctor_names(ctx: ModuleContext) -> Set[str]:
    """Local names PartitionSpec/NamedSharding are importable under
    (aliases included) — the constructors the layout module monopolizes."""
    names = set(_SPEC_CTORS)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in _SPEC_CTORS:
                    names.add(alias.asname or alias.name)
    return names


@rule("spec-literal-outside-layout", "error",
      "PartitionSpec/NamedSharding constructed outside the layout module")
def check_spec_literal_outside_layout(ctx: ModuleContext) -> Iterable[Finding]:
    """Everywhere except the canonical layout module (config
    `shard-modules` — parallel/speclayout.py), constructing a
    PartitionSpec or NamedSharding (or importing one, which is how the
    literal would sneak in) is a finding. The SpecLayout is the ONE source
    of partition specs: a hand-rolled spec at a call site is exactly the
    per-site drift the layout module exists to make impossible — it would
    compile, shard wrong (or silently replicate), and only surface in the
    multichip suite."""
    if ctx.path_matches(ctx.config.shard_modules):
        return
    names = _sharding_ctor_names(ctx)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in _SPEC_CTORS:
                    yield ctx.finding(
                        node, f"import of {alias.name} outside the layout "
                              f"module — ask the canonical SpecLayout "
                              f"(parallel/speclayout.py) for specs/"
                              f"shardings instead")
        elif isinstance(node, ast.Call) and _terminal(node.func) in names:
            yield ctx.finding(
                node, f"{_terminal(node.func)}(...) constructed outside "
                      f"the layout module — every partition spec must come "
                      f"from the canonical SpecLayout "
                      f"(parallel/speclayout.py)")
