"""leakguard: whole-program resource-lifecycle analysis.

The north star is a service absorbing heavy traffic for months: every
thread, timer, executor, socket, file handle, HTTP server, temp dir and
device-pool entry acquired per start()/query/stop() cycle must be provably
released, or the process bleeds until a wedged run (the BENCH_r05 /
MULTICHIP_r05 rc=124 shape) or an OOM. Every recent PR found this bug
class BY HAND — the FileEmitter handle leak, the devicepool finalizer
self-deadlock, the emitter-vs-shutdown race, the stop() un-chaining bugs
in both server types. leakguard closes the static-analysis triad's missing
leg next to druidlint/tracecheck/raceguard by making the discipline
mechanical.

It rides raceguard's whole-program index (module set = config
`raceguard-modules`): the binder types attribute owners, the per-function
event walk already records calls/acquisitions, and the same
program-signature cache keying keeps cross-module findings sound. On top
of that index leakguard discovers ACQUISITION SITES — constructor calls
whose result pins an OS or device resource — binds each to an OWNER (the
class whose attribute, or the module global, holds it), and checks
release reachability from the owner's shutdown surface.

Five rules ride the shared registry/baseline/suppression/cache machinery
(suppress with `# druidlint: disable=<rule>  # <rationale>`):

  unreleased-resource   an owned acquisition (executor, HTTP server, file,
                        socket, TemporaryDirectory, mmap, or a service
                        whose constructor starts a thread) with no release
                        call reachable from the owner's stop()/close()/
                        shutdown()/__exit__;
  unjoined-thread       an owned STARTED Thread/Timer that is never
                        joined, not joined on any shutdown path, or only
                        joined without a timeout on shutdown paths (a hung
                        worker then hangs every stop() above it);
  stop-start-pairing    a class with start() whose __init__/start wires
                        itself into FOREIGN state (chaining another
                        object's attribute) without stop() undoing that
                        wiring — the identity-guarded un-chain idiom PRs 6
                        and 7 had to hand-enforce;
  leak-on-error-path    a local acquisition followed by a raise-capable
                        statement before ownership transfer, outside any
                        try — the constructor raises and the handle leaks;
  finalizer-unsafe      a weakref.finalize callback or __del__ whose call
                        closure acquires a lock — GC runs finalizers at
                        arbitrary allocation points, including while the
                        very lock is held (the PR 5 devicepool witness
                        bug, now caught statically).

Dynamic complement: tools/druidlint/leakwitness.py snapshots live threads,
open fds and devicepool resident bytes around the test suite
(DRUID_TPU_LEAK_WITNESS=1) and asserts return-to-baseline — the witness
catches what the model cannot see, exactly like lockwitness does for the
lock-order graph.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from types import SimpleNamespace
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.druidlint.core import Finding, ModuleContext, rule
from tools.druidlint.rules import _FUNC_DEFS, _terminal
from tools.druidlint.raceguard import (INIT_METHODS, Program, Site, _Scope,
                                       _class_with, _closure_frames,
                                       _frame_of, _own, _resolve_value,
                                       _self_param, analyze_sources)

# ---------------------------------------------------------------------------
# Resource model
# ---------------------------------------------------------------------------

#: constructor terminal name → resource kind (direct stdlib acquisitions)
ACQ_CTORS = {
    "Thread": "thread", "Timer": "thread",
    "ThreadPoolExecutor": "executor", "ProcessPoolExecutor": "executor",
    "ThreadingHTTPServer": "server", "HTTPServer": "server",
    "ThreadingTCPServer": "server", "TCPServer": "server",
    "UDPServer": "server", "ThreadingUDPServer": "server",
    "open": "file",
    "socket": "socket", "create_connection": "socket",
    "TemporaryDirectory": "tempdir",
    "mmap": "mmap", "memmap": "mmap",
}

#: stdlib server base-class names: a program class deriving one of these
#: is itself a server acquisition when constructed
SERVER_BASES = {"ThreadingHTTPServer", "HTTPServer", "ThreadingTCPServer",
                "TCPServer", "UDPServer", "ThreadingUDPServer",
                "BaseServer", "socketserver"}

#: kind → method names any one of which releases the resource
RELEASES = {
    "thread": {"join"},
    "executor": {"shutdown"},
    "server": {"server_close", "close"},
    "file": {"close"},
    "socket": {"close", "detach"},
    "tempdir": {"cleanup"},
    "mmap": {"close"},
    "service": {"stop", "close", "shutdown"},
}

#: what a human should call, for messages
RELEASE_HINT = {
    "thread": ".join(timeout=...)", "executor": ".shutdown()",
    "server": ".server_close()", "file": ".close()", "socket": ".close()",
    "tempdir": ".cleanup()", "mmap": ".close()",
    "service": ".stop()/.close()",
}

#: the owner's shutdown surface: release must be reachable from one of
#: these (when the owner defines any of them)
ENTRY_METHODS = {"stop", "close", "shutdown", "__exit__", "cleanup",
                 "uninstall", "terminate"}

#: kinds leak-on-error-path tracks for LOCAL variables (an unstarted
#: Thread object holds no OS resource yet)
LOCAL_LEAK_KINDS = {"file", "socket", "tempdir", "mmap", "executor",
                    "server"}

#: container-read methods whose result is an element of the attr
_DERIVE_GETTERS = {"get", "pop", "popleft", "popitem", "setdefault"}

@dataclass
class Acq:
    kind: str
    owner: Optional[str]              # class_key, or None for module global
    attr: str                         # attribute name / global name
    site: Site
    path: str


@dataclass
class Release:
    attr: str
    method: str                       # join/close/shutdown/…
    fid: str                          # function it occurs in
    has_timeout: bool
    site: Site


@dataclass
class _ClassLeaks:
    acqs: List[Acq] = field(default_factory=list)
    releases: List[Release] = field(default_factory=list)
    #: attrs whose value was handed to a Lifecycle-style registrar or
    #: returned/escaped — ownership transferred, owner no longer on the
    #: hook for the release
    escaped_attrs: Set[str] = field(default_factory=set)
    started_attrs: Set[str] = field(default_factory=set)


# ---------------------------------------------------------------------------
# Discovery
# ---------------------------------------------------------------------------

def _threaded_ctor_classes(prog: Program) -> Set[str]:
    """Program classes whose __init__ both CONSTRUCTS and STARTS a thread:
    holding such an instance is holding a running thread, so the holder
    must stop it (the EventReceiver/TaskActionServer/LoadQueuePeon/
    BatchingEmitter shape)."""
    out: Set[str] = set()
    for ck, ci in prog.classes.items():
        init = ci.methods.get("__init__")
        if init is None:
            continue
        fi = prog.funcs[init]
        ctor = started = False
        for node in _own(fi):
            if isinstance(node, ast.Call):
                name = _terminal(node.func)
                if name in ("Thread", "Timer"):
                    ctor = True
                elif name == "start":
                    started = True
        if ctor and started:
            out.add(ck)
    return out


def _acq_kind(prog: Program, mod, scope: _Scope, call: ast.Call,
              services: Set[str]) -> Optional[str]:
    """Resource kind of a constructor call. "service" = a program class
    whose ctor starts a thread; "service?" = a program class with a
    start()+stop() surface — it only becomes an acquisition if the owner
    actually start()s the attribute (resolved by the caller)."""
    name = _terminal(call.func)
    kind = ACQ_CTORS.get(name)
    if kind is not None:
        # bare `open` only as a Name or os./io. prefix; `self.open(...)`
        # is a method call, not the builtin
        if kind == "file" and isinstance(call.func, ast.Attribute) \
                and _terminal(call.func.value) not in ("os", "io",
                                                       "gzip", "bz2",
                                                       "lzma"):
            return None
        return kind
    got = _resolve_value(prog, mod, scope, call.func)
    if got is not None and got[0] == "class":
        ci = prog.classes.get(got[1])
        if ci is not None:
            if any(_terminal(b) in SERVER_BASES for b in ci.bases):
                return "server"
            has_release = bool(set(ci.methods) & RELEASES["service"])
            if got[1] in services and has_release:
                return "service"
            if "start" in ci.methods and has_release:
                return "service?"
    return None


def _src_order(fi) -> List[ast.AST]:
    """fi's own nodes in source order (the _own DFS stack order is not)."""
    return sorted((n for n in _own(fi) if hasattr(n, "lineno")),
                  key=lambda n: (n.lineno, n.col_offset))


def _self_attr(expr: ast.AST, self_name: Optional[str]) -> Optional[str]:
    """`self.X` → "X" (None otherwise)."""
    if self_name is not None and isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == self_name:
        return expr.attr
    return None


_SNAPSHOT_FNS = {"list", "sorted", "tuple", "set", "reversed", "iter"}


def _derived_locals(fi, self_name: Optional[str]) -> Dict[str, str]:
    """Local name → attr it derives from: `t = self._thread`,
    `t = self._threads[k]`, `t = self._threads.pop(k)`, loop targets over
    `self._threads` / `.values()` / `.items()`, snapshot wrappers
    (`ts = list(self._threads.values())` — the take-under-the-lock idiom
    the lock-scope rule forces), and transitively through locals."""
    out: Dict[str, str] = {}

    def origin(expr) -> Optional[str]:
        attr = _self_attr(expr, self_name)
        if attr is not None:
            return attr
        if isinstance(expr, ast.Name):
            return out.get(expr.id)
        if isinstance(expr, ast.Subscript):
            return origin(expr.value)
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr in (_DERIVE_GETTERS
                                           | {"values", "items"}):
                return origin(expr.func.value)
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in _SNAPSHOT_FNS and expr.args:
                return origin(expr.args[0])
        return None

    for node in _src_order(fi):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            o = origin(node.value)
            if o is not None:
                out[node.targets[0].id] = o
        elif isinstance(node, (ast.For, ast.comprehension)):
            it = node.iter
            o = origin(it)
            if o is None:
                continue
            tgt = node.target
            if isinstance(tgt, ast.Name):
                out[tgt.id] = o
            elif isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2 \
                    and isinstance(tgt.elts[1], ast.Name) \
                    and isinstance(it, ast.Call) \
                    and _terminal(it.func) == "items":
                out[tgt.elts[1].id] = o     # for k, v in self.X.items()
    return out


def _collect_class(prog: Program, ck: str,
                   services: Set[str]) -> _ClassLeaks:
    ci = prog.classes[ck]
    mod = prog.modules[ci.path]
    out = _ClassLeaks()
    all_release_names = set().union(*RELEASES.values())
    for mname, fid in ci.methods.items():
        fi = prog.funcs[fid]
        self_name = _self_param(fi.node)
        if self_name is None:
            continue
        scope = _Scope(mod, _closure_frames(prog, mod, fi)
                       + [_frame_of(prog, mod, fi)])
        derived = _derived_locals(fi, self_name)
        #: locals holding a fresh acquisition in this function
        local_acq: Dict[str, str] = {}
        #: local name → attr it was stored into (`self.X[k] = t`)
        local_home: Dict[str, str] = {}
        for node in _src_order(fi):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(node.value, ast.Call):
                    kind = _acq_kind(prog, mod, scope, node.value, services)
                    attr = _self_attr(t, self_name)
                    if kind is not None and attr is not None:
                        out.acqs.append(Acq(kind, ck, attr,
                                            Site(ci.path,
                                                 node.value.lineno,
                                                 node.value.col_offset),
                                            ci.path))
                    elif kind is not None and isinstance(t, ast.Name):
                        local_acq[t.id] = kind
                    elif kind is not None and isinstance(t, ast.Subscript):
                        cattr = _self_attr(t.value, self_name)
                        if cattr is not None:
                            out.acqs.append(Acq(kind, ck, cattr,
                                                Site(ci.path,
                                                     node.value.lineno,
                                                     node.value.col_offset),
                                                ci.path))
                elif isinstance(node.value, ast.Name) \
                        and node.value.id in local_acq:
                    # two-step ownership: `t = Thread(...); self.X = t`
                    # (or container store `self.X[k] = t`)
                    kind = local_acq[node.value.id]
                    attr = _self_attr(t, self_name)
                    if attr is None and isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value, self_name)
                    if attr is not None:
                        out.acqs.append(Acq(kind, ck, attr,
                                            Site(ci.path, node.lineno,
                                                 node.col_offset),
                                            ci.path))
                        local_home[node.value.id] = attr
            elif isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute):
                    recv = func.value
                    attr = _self_attr(recv, self_name)
                    if attr is None and isinstance(recv, ast.Subscript):
                        attr = _self_attr(recv.value, self_name)
                    if attr is None and isinstance(recv, ast.Name):
                        attr = derived.get(recv.id)
                    if attr is not None:
                        if func.attr == "start":
                            out.started_attrs.add(attr)
                        elif func.attr in all_release_names:
                            has_to = bool(node.args) or any(
                                kw.arg == "timeout"
                                for kw in node.keywords)
                            out.releases.append(Release(
                                attr, func.attr, fid, has_to,
                                Site(ci.path, node.lineno,
                                     node.col_offset)))
                # `t.start()` on a local that was stored into (or read
                # out of) an attr container marks that attr started (the
                # ForkingTaskRunner start-outside-the-lock shape)
                if isinstance(func, ast.Attribute) \
                        and func.attr == "start" \
                        and isinstance(func.value, ast.Name):
                    home = local_home.get(func.value.id) \
                        or derived.get(func.value.id)
                    if home is not None:
                        out.started_attrs.add(home)
                # bare `self.X` as an argument = ownership escapes (a
                # Lifecycle.add(self._monitors) registrar now owns the
                # stop; a callback receiver may close it) — but ONLY when
                # the callee can actually close it: a points-to pass over
                # resolvable program callees keeps the obligation here
                # when the receiving parameter is provably never
                # released, stored, returned, or re-escaped (the PR 14
                # rider; unresolvable callees stay conservative)
                for pos, arg in enumerate(node.args):
                    attr = _self_attr(arg, self_name)
                    if attr is not None and _callee_can_close(
                            prog, mod, scope, node, pos, None):
                        out.escaped_attrs.add(attr)
                for kw in node.keywords:
                    attr = _self_attr(kw.value, self_name)
                    if attr is not None and _callee_can_close(
                            prog, mod, scope, node, None, kw.arg):
                        out.escaped_attrs.add(attr)
            elif isinstance(node, ast.Return) and node.value is not None:
                attr = _self_attr(node.value, self_name)
                if attr is not None:
                    out.escaped_attrs.add(attr)
    return out


# ---------------------------------------------------------------------------
# Points-to: can a callee close the attribute handed to it?
# ---------------------------------------------------------------------------

#: transitive-escape recursion bound: past this depth the pass answers
#: "yes, it can close it" (the pre-pass conservative default)
_POINTS_TO_DEPTH = 3


def _callee_can_close(prog: Program, mod, scope: _Scope, call: ast.Call,
                      pos: Optional[int], kw_name: Optional[str],
                      depth: int = 0) -> bool:
    """True when passing an owned attribute as this call argument may
    transfer the release obligation. Conservative by default (unknown or
    external callees, constructors, varargs, re-escapes all answer True);
    False ONLY when the callee resolves to a program function whose
    receiving parameter is provably inert — never the receiver of a
    release-family method, never stored into an attribute/subscript,
    never returned/yielded, never a context manager, and never passed on
    to anything that could itself close it (followed transitively to
    _POINTS_TO_DEPTH)."""
    if depth >= _POINTS_TO_DEPTH:
        return True
    got = _resolve_value(prog, mod, scope, call.func)
    if got is None or got[0] == "class":
        return True                       # unknown / constructor stores it
    if got[0] != "func":
        return True
    fi = prog.funcs.get(got[1])
    if fi is None or isinstance(fi.node, ast.Lambda):
        return True
    args = fi.node.args
    if args.vararg is not None or args.kwarg is not None:
        return True
    params = [a.arg for a in getattr(args, "posonlyargs", [])] \
        + [a.arg for a in args.args]
    if fi.class_key is not None and isinstance(call.func, ast.Attribute) \
            and params:
        params = params[1:]               # bound call: drop self
    if kw_name is not None:
        pname = kw_name if kw_name in params \
            or kw_name in {a.arg for a in args.kwonlyargs} else None
    else:
        pname = params[pos] if pos is not None and pos < len(params) \
            else None
    if pname is None:
        return True
    return _param_can_be_closed(prog, fi, pname, depth)


def _param_can_be_closed(prog: Program, fi, pname: str,
                         depth: int) -> bool:
    """Whether `pname` inside `fi` can end up closed/owned elsewhere.
    Tracks direct uses plus simple local aliases (`x = pname`)."""
    all_release_names = set().union(*RELEASES.values())
    names = {pname}
    #: names the function declares global/nonlocal: a store to one is an
    #: ownership transfer, not a local alias
    outer_names: Set[str] = set()
    for node in _own(fi):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            outer_names.update(node.names)
    for node in _src_order(fi):           # aliases first, source order
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id not in outer_names \
                and isinstance(node.value, ast.Name) \
                and node.value.id in names:
            names.add(node.targets[0].id)

    def is_tracked(expr) -> bool:
        return isinstance(expr, ast.Name) and expr.id in names

    def holds_tracked(expr) -> bool:
        """The VALUE being stored/returned holds the resource itself: the
        bare name, or the name inside (nested) tuple/list/set/dict
        containers. Derived expressions (an f-string reading an
        attribute, arithmetic) yield new objects, not the handle."""
        if is_tracked(expr):
            return True
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(holds_tracked(e) for e in expr.elts)
        if isinstance(expr, ast.Dict):
            return any(v is not None and holds_tracked(v)
                       for v in expr.values)
        if isinstance(expr, ast.Starred):
            return holds_tracked(expr.value)
        return False

    mod = prog.modules[fi.path]
    scope = _Scope(mod, _closure_frames(prog, mod, fi)
                   + [_frame_of(prog, mod, fi)])
    # a closure (nested def/lambda) capturing the parameter can release
    # it later from anywhere — conservative escape
    for node in ast.walk(fi.node):
        if isinstance(node, _FUNC_DEFS + (ast.Lambda,)) \
                and node is not fi.node:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id in names:
                    return True
    for node in _own(fi):
        if isinstance(node, ast.Attribute) and is_tracked(node.value) \
                and node.attr in all_release_names:
            # any REFERENCE to a release-family attribute of the param —
            # `param.close()` but also a bound-method value like
            # `sinks.append(param.close)` — can release it
            return True
        if isinstance(node, ast.Call):
            # param passed onward: recurse (bounded); unresolvable → True
            for i, arg in enumerate(node.args):
                if is_tracked(arg) and _callee_can_close(
                        prog, mod, scope, node, i, None, depth + 1):
                    return True
            for kw in node.keywords:
                if is_tracked(kw.value) and _callee_can_close(
                        prog, mod, scope, node, None, kw.arg, depth + 1):
                    return True
        elif isinstance(node, ast.Assign):
            # stored into an attribute/subscript or a global/nonlocal
            # name: ownership taken (any tracked name anywhere in the
            # stored value counts — tuples, method references, wrappers)
            for t in node.targets:
                outer = isinstance(t, ast.Name) and t.id in outer_names
                if (isinstance(t, (ast.Attribute, ast.Subscript))
                        or outer) and holds_tracked(node.value):
                    return True
        elif isinstance(node, (ast.Return, ast.Yield)) \
                and getattr(node, "value", None) is not None:
            if holds_tracked(node.value):
                return True
        elif isinstance(node, ast.With):
            for item in node.items:
                if is_tracked(item.context_expr):
                    return True           # __exit__ closes it
    return False


# ---------------------------------------------------------------------------
# Reachability
# ---------------------------------------------------------------------------

def _self_closure(prog: Program, ck: str, entry_names: Set[str],
                  include_free: bool = False) -> Set[str]:
    """func_ids reachable from the named methods of `ck` following
    self-receiver call edges (and, optionally, calls into free module
    functions — the compose_sink-style helper shape)."""
    ci = prog.classes.get(ck)
    if ci is None:
        return set()
    seen: Set[str] = set()
    stack = [fid for name, fid in ci.methods.items()
             if name in entry_names]
    while stack:
        fid = stack.pop()
        if fid in seen:
            continue
        seen.add(fid)
        fi = prog.funcs.get(fid)
        if fi is None:
            continue
        for callee, _h, _s, recv_self in fi.calls:
            tfi = prog.funcs.get(callee)
            if tfi is None:
                continue
            same_class = tfi.class_key == ck
            free = tfi.class_key is None
            if recv_self or same_class or (include_free and free):
                stack.append(callee)
    return seen


def _entry_methods_of(prog: Program, ck: str) -> Set[str]:
    ci = prog.classes[ck]
    return {m for m in ci.methods if m in ENTRY_METHODS}


# ---------------------------------------------------------------------------
# Rules: unreleased-resource + unjoined-thread
# ---------------------------------------------------------------------------

def _check_ownership(prog: Program, add) -> None:
    services = _threaded_ctor_classes(prog)
    for ck in sorted(prog.classes):
        cl = _collect_class(prog, ck, services)
        if not cl.acqs:
            continue
        entries = _entry_methods_of(prog, ck)
        entry_closure = _self_closure(prog, ck, entries) if entries \
            else set()
        rel_by_attr: Dict[str, List[Release]] = {}
        for r in cl.releases:
            rel_by_attr.setdefault(r.attr, []).append(r)
        seen_attr_kinds: Set[Tuple[str, str]] = set()
        for acq in cl.acqs:
            if acq.kind == "service?":
                # a held start/stop service only becomes our resource if
                # WE start it (tests constructing-but-never-starting one
                # owe nothing)
                if acq.attr not in cl.started_attrs:
                    continue
                acq.kind = "service"
            key = (acq.attr, acq.kind)
            if key in seen_attr_kinds:
                continue              # one finding per (attr, kind)
            seen_attr_kinds.add(key)
            if acq.attr in cl.escaped_attrs:
                continue              # ownership handed off — not ours
            rels = [r for r in rel_by_attr.get(acq.attr, ())
                    if r.method in RELEASES[acq.kind]]
            if acq.kind == "thread":
                if acq.attr not in cl.started_attrs:
                    continue          # never started: no OS thread to join
                if not rels:
                    add("unjoined-thread", acq.site,
                        f"{_short(ck)}.{acq.attr} thread is start()ed but "
                        f"never joined — stop() returns while the worker "
                        f"still runs, and a million start/stop cycles "
                        f"strand a million threads; join it (with a "
                        f"timeout) on the shutdown path")
                    continue
                if entries:
                    on_path = [r for r in rels if r.fid in entry_closure]
                    if not on_path:
                        add("unjoined-thread", acq.site,
                            f"{_short(ck)}.{acq.attr} thread is joined, "
                            f"but not on any shutdown path "
                            f"({'/'.join(sorted(entries))}) — stop() can "
                            f"return with the worker still running")
                    elif all(not r.has_timeout for r in on_path):
                        add("unjoined-thread", on_path[0].site,
                            f"{_short(ck)}.{acq.attr}.join() without a "
                            f"timeout on a shutdown path — a wedged "
                            f"worker then hangs every stop() above it; "
                            f"pass a bounded timeout")
                continue
            # non-thread kinds → unreleased-resource
            if not rels:
                add("unreleased-resource", acq.site,
                    f"{_short(ck)}.{acq.attr} ({acq.kind}) is acquired "
                    f"but no release ({RELEASE_HINT[acq.kind]}) exists "
                    f"anywhere in {_short(ck)} — every owner lifecycle "
                    f"leaks one; release it from "
                    f"stop()/close()/shutdown()")
            elif entries and not any(r.fid in entry_closure for r in rels):
                rel = min(rels, key=lambda r: (r.site.path, r.site.line))
                add("unreleased-resource", acq.site,
                    f"{_short(ck)}.{acq.attr} ({acq.kind}) is released "
                    f"only outside the shutdown surface (release at "
                    f"{rel.site.path}:{rel.site.line} is not reachable "
                    f"from {'/'.join(sorted(entries))}) — a plain stop() "
                    f"leaks it")


# ---------------------------------------------------------------------------
# Rule: leak-on-error-path
# ---------------------------------------------------------------------------

def _check_error_paths(prog: Program, add) -> None:
    services: Set[str] = set()        # service kind not tracked for locals
    for fid in sorted(prog.funcs):
        fi = prog.funcs[fid]
        mod = prog.modules[fi.path]
        scope = _Scope(mod, _closure_frames(prog, mod, fi)
                       + [_frame_of(prog, mod, fi)])
        def walk_block(body, in_try: bool):
            #: name → (site, kind) acquired and not yet transferred
            pending: Dict[str, Tuple[Site, str]] = {}
            for node in body:
                if isinstance(node, _FUNC_DEFS + (ast.ClassDef,)):
                    continue
                if isinstance(node, ast.Try):
                    # anything pending is now covered by a handler/finally
                    pending.clear()
                    for sub in ([node.body, node.orelse, node.finalbody]
                                + [h.body for h in node.handlers]):
                        walk_block(sub, True)
                    continue
                if isinstance(node, ast.With):
                    # `with open(...) as f`: the manager releases
                    for item in node.items:
                        _transfer_names(item.context_expr, pending)
                    walk_block(node.body, in_try)
                    continue
                # 1) transfers in this statement clear pending
                acquired_here: Set[str] = set()
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(node.value, ast.Call) \
                            and isinstance(t, ast.Name) and not in_try:
                        kind = _acq_kind(prog, mod, scope, node.value,
                                         services)
                        if kind in LOCAL_LEAK_KINDS:
                            pending[t.id] = (Site(fi.path,
                                                  node.value.lineno,
                                                  node.value.col_offset),
                                             kind)
                            acquired_here.add(t.id)
                    if isinstance(node.value, ast.Name):
                        pending.pop(node.value.id, None)  # stored → owned
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        for arg in (list(sub.args)
                                    + [kw.value for kw in sub.keywords]):
                            _transfer_names(arg, pending)
                    elif isinstance(sub, (ast.Return, ast.Yield)) \
                            and getattr(sub, "value", None) is not None:
                        _transfer_names(sub.value, pending)
                # 2) a raise-capable statement with acquisitions pending
                #    (not acquired by this very statement) leaks on raise
                at_risk = {n: ps for n, ps in pending.items()
                           if n not in acquired_here}
                if at_risk and _raise_capable(node, set(at_risk)):
                    for name, (site, kind) in sorted(at_risk.items()):
                        add("leak-on-error-path", site,
                            f"local {kind} `{name}` is acquired here, and "
                            f"a later call can raise before ownership "
                            f"transfers — the handle leaks on that path; "
                            f"use a context manager or try/finally")
                        pending.pop(name, None)
                # nested control flow inherits pending? conservative: a
                # branch may transfer — drop pending entering branches
                if any(getattr(node, b, None)
                       for b in ("body", "orelse", "finalbody")):
                    for sub in (getattr(node, "body", None),
                                getattr(node, "orelse", None),
                                getattr(node, "finalbody", None)):
                        if sub:
                            walk_block(sub, in_try)
                    pending.clear()

        walk_block(fi.node.body if not isinstance(fi.node, ast.Lambda)
                   else [], False)


def _transfer_names(expr: ast.AST, pending: Dict[str, Tuple]) -> None:
    if isinstance(expr, ast.Name):
        pending.pop(expr.id, None)
    elif isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            _transfer_names(e, pending)


def _raise_capable(node: ast.AST, pending_names: Set[str]) -> bool:
    """A statement that can raise mid-flight: any call NOT on a pending
    resource itself (fh.write() raising still leaks fh, but the common
    `fh = open(); self._fh = fh` shape must stay quiet), or an explicit
    raise/assert."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Raise, ast.Assert)):
            return True
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in pending_names:
                continue              # method on the resource itself
            return True
    return False


# ---------------------------------------------------------------------------
# Rule: finalizer-unsafe
# ---------------------------------------------------------------------------

def _call_closure(prog: Program, fid: str) -> Set[str]:
    seen: Set[str] = set()
    stack = [fid]
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        fi = prog.funcs.get(cur)
        if fi is None:
            continue
        for callee, _h, _s, _r in fi.calls:
            stack.append(callee)
    return seen


def _check_finalizers(prog: Program, add) -> None:
    #: (registration site, callback fid, label)
    finalizers: List[Tuple[Site, str, str]] = []
    for fid in sorted(prog.funcs):
        fi = prog.funcs[fid]
        mod = prog.modules[fi.path]
        scope = _Scope(mod, _closure_frames(prog, mod, fi)
                       + [_frame_of(prog, mod, fi)])
        for node in _own(fi):
            if isinstance(node, ast.Call) \
                    and _terminal(node.func) == "finalize" \
                    and len(node.args) >= 2:
                got = _resolve_value(prog, mod, scope, node.args[1])
                if got is not None and got[0] == "func":
                    finalizers.append(
                        (Site(fi.path, node.lineno, node.col_offset),
                         got[1], "weakref.finalize callback"))
    for ck, ci in prog.classes.items():
        if "__del__" in ci.methods:
            fid = ci.methods["__del__"]
            fi = prog.funcs[fid]
            finalizers.append(
                (Site(ci.path, fi.node.lineno, fi.node.col_offset),
                 fid, f"{_short(ck)}.__del__"))
    for site, fid, label in finalizers:
        for member in sorted(_call_closure(prog, fid)):
            mfi = prog.funcs.get(member)
            if mfi is None or not mfi.acquires:
                continue
            lock, _h, lsite, _w = mfi.acquires[0]
            add("finalizer-unsafe", site,
                f"{label} reaches a lock acquisition "
                f"({mfi.qual}() at {lsite.path}:{lsite.line}) — GC runs "
                f"finalizers at arbitrary allocation points, including "
                f"while that very lock is held: self-deadlock. Enqueue "
                f"into a lock-free structure drained under the lock "
                f"instead (the devicepool._dead_owners idiom)")
            break


# ---------------------------------------------------------------------------
# Rule: stop-start-pairing
# ---------------------------------------------------------------------------

def _check_pairing(prog: Program, add) -> None:
    # index: state → [(fid, site)] of every attribute write in the program
    writes_by_state: Dict[Tuple, List[Tuple[str, Site]]] = {}
    for fid, fi in prog.funcs.items():
        for st, _held, site in fi.writes:
            if st[0] != "attr":
                continue
            writes_by_state.setdefault(st, []).append((fid, site))
    for ck in sorted(prog.classes):
        ci = prog.classes[ck]
        if "start" not in ci.methods:
            continue
        wiring_closure = _self_closure(prog, ck, {"__init__", "start"},
                                       include_free=True)
        stop_closure = _self_closure(
            prog, ck, _entry_methods_of(prog, ck), include_free=True)
        #: classes this class constructs itself (their attrs die with us)
        constructed: Set[str] = set()
        init = ci.methods.get("__init__")
        if init is not None:
            fi = prog.funcs[init]
            mod = prog.modules[ci.path]
            scope = _Scope(mod, [_frame_of(prog, mod, fi)])
            for node in _own(fi):
                if isinstance(node, ast.Call):
                    got = _resolve_value(prog, mod, scope, node.func)
                    if got is not None and got[0] == "class":
                        constructed.add(got[1])
        for fid in sorted(wiring_closure):
            fi = prog.funcs[fid]
            for st, _held, site in fi.writes:
                if st[0] != "attr" or st[1] == ck:
                    continue          # own state is not wiring
                if st[1] in constructed:
                    continue          # we own that object's lifetime
                if fi.class_key is not None and fi.class_key != ck:
                    continue          # another class's method: its problem
                # undo present? (a) same state written in stop closure
                undone = any(w_fid in stop_closure and w_site != site
                             for w_fid, w_site
                             in writes_by_state.get(st, ()))
                # (b) the wiring function (or a nested local fn of it)
                #     also writes the state — the compose_sink restore
                #     closure idiom
                if not undone:
                    prefix = fi.qual + ".<locals>."
                    for w_fid, w_site in writes_by_state.get(st, ()):
                        wfi = prog.funcs.get(w_fid)
                        if wfi is None:
                            continue
                        if w_site != site and wfi.path == fi.path and (
                                w_fid == fid
                                or wfi.qual.startswith(prefix)):
                            undone = True
                            break
                if not undone:
                    add("stop-start-pairing", site,
                        f"{_short(ck)} wires foreign state "
                        f"{_short(st[1])}.{st[2]} here (during "
                        f"__init__/start) but no stop()/close() path "
                        f"writes it back — a reconstructed "
                        f"{_short(ck)} over the same object double-"
                        f"chains; restore the previous value "
                        f"(identity-guarded) on stop")


def _short(class_key: str) -> str:
    return class_key.split("::", 1)[-1]


# ---------------------------------------------------------------------------
# Orchestration + rule shims
# ---------------------------------------------------------------------------

def leak_findings(prog: Program) -> Dict[str, Dict[str, List[Tuple]]]:
    """rule → path → [(line, col, message)], memoized on the Program."""
    got = getattr(prog, "_leak_findings", None)
    if got is not None:
        return got
    findings: Dict[str, Dict[str, List[Tuple]]] = {}

    def add(rule_name: str, site: Site, message: str) -> None:
        findings.setdefault(rule_name, {}).setdefault(
            site.path, []).append((site.line, site.col, message))

    _check_ownership(prog, add)
    _check_error_paths(prog, add)
    _check_finalizers(prog, add)
    _check_pairing(prog, add)
    prog._leak_findings = findings
    return findings


def _program_for(ctx: ModuleContext) -> Program:
    from tools.druidlint.raceguard import _program_for as rg_program
    return rg_program(ctx)


def _emit(ctx: ModuleContext, rule_name: str) -> Iterable[Finding]:
    if not ctx.path_matches(ctx.config.raceguard_modules):
        return
    prog = _program_for(ctx)
    for line, col, message in sorted(
            leak_findings(prog).get(rule_name, {}).get(ctx.path, ())):
        yield ctx.finding(SimpleNamespace(lineno=line, col_offset=col),
                          message)


@rule("unreleased-resource", "error",
      "owned resource with no release reachable from the shutdown surface")
def check_unreleased_resource(ctx: ModuleContext) -> Iterable[Finding]:
    """A class-owned acquisition (executor, HTTP server, file, socket,
    TemporaryDirectory, mmap, threaded service) whose release call is
    absent — or present but unreachable from the owner's
    stop()/close()/shutdown()/__exit__. Passing the attribute to another
    object (a Lifecycle registrar) transfers ownership and silences the
    rule. Whole-program: uses raceguard's binder and module set."""
    yield from _emit(ctx, "unreleased-resource")


@rule("unjoined-thread", "error",
      "owned started thread never joined (or join has no timeout)")
def check_unjoined_thread(ctx: ModuleContext) -> Iterable[Finding]:
    """An attribute-held Thread/Timer that is start()ed but never joined,
    joined only off the shutdown surface, or joined without a timeout on
    it. Fire-and-forget locals are exempt (request-scoped); stored threads
    are infrastructure and must be joined boundedly on stop()."""
    yield from _emit(ctx, "unjoined-thread")


@rule("stop-start-pairing", "warning",
      "start()-time wiring into foreign state with no stop()-time undo")
def check_stop_start_pairing(ctx: ModuleContext) -> Iterable[Finding]:
    """A class with start() that rebinds ANOTHER object's attribute during
    __init__/start (chaining a lifecycle hook, swapping an emitter sink)
    must write it back on its stop path — or carry the undo as a nested
    restore closure at the wiring site (the compose_sink idiom). Otherwise
    server generations double-chain and dead references accumulate."""
    yield from _emit(ctx, "stop-start-pairing")


@rule("leak-on-error-path", "warning",
      "local acquisition can leak when a later call raises")
def check_leak_on_error_path(ctx: ModuleContext) -> Iterable[Finding]:
    """`fh = open(...)` followed by a raise-capable call before the handle
    is stored/returned/passed on, with no enclosing try: the exception
    unwinds and the fd leaks. Use a context manager, try/finally, or
    transfer ownership first."""
    yield from _emit(ctx, "leak-on-error-path")


@rule("finalizer-unsafe", "error",
      "weakref/__del__ finalizer acquires a lock in its call closure")
def check_finalizer_unsafe(ctx: ModuleContext) -> Iterable[Finding]:
    """GC may run a finalizer at ANY allocation point — including while the
    thread holds the very lock the finalizer wants (the PR 5 devicepool
    self-deadlock). Finalizer callbacks must stay lock-free: enqueue into
    an atomic structure and drain it under the lock from normal code."""
    yield from _emit(ctx, "finalizer-unsafe")
