"""stallwitness: a dynamic witness for stallguard's deadline discipline.

stallguard proves statically that every request-path park carries a
bound — but a static bound is a claim about ARGUMENTS, not about time:
a park can receive a "timeout" that is None at runtime, or a bound so
large it is indistinguishable from forever. The witness closes that loop
by observing reality: it wraps the blocking primitives the analyzer
models (`threading.Event.wait`, `threading.Condition.wait`,
`threading.Thread.join`, `queue.Queue.get`, `subprocess.Popen.wait`,
`time.sleep`) and, for every park issued FROM a druid_tpu source site,
records the site, whether a timeout was actually supplied, and the
longest observed park duration. An UNTIMED park on any thread that is
not inside a shutdown scope is a violation — exactly the
parked-forever handler the static rules exist to prevent, caught in
vivo.

Mechanics:
  * install() swaps the class/module attributes for recording wrappers
    (keywitness's `_saved` restore-in-reverse idiom). Eligibility is
    lockwitness's caller-frame rule: the immediate caller's file must be
    repo-relative under a configured prefix, so stdlib-internal parks
    (Event.wait delegating to Condition.wait inside threading.py) are
    neither double-counted nor misattributed, and test code parks free.
  * An untimed park is excused only in a SHUTDOWN SCOPE: some frame on
    the current stack is a recognized teardown entry (stop/close/
    shutdown/__exit__/cleanup/terminate/...). Joining a worker forever
    from stop() is a policy choice; parking a request thread forever is
    a bug.
  * `threading.Lock.acquire` is a C slot on an extension type and cannot
    be patched; lock parks are lockwitness's domain (its WitnessLock
    wrapper already times acquisition). Socket/HTTP parks are bounded at
    the urlopen(timeout=...) layer, which stallguard checks statically.
  * time.sleep is recorded (max-duration ledger) but always counts as
    timed — its bound IS its argument; the static sleep-on-request-path
    rule owns the policy question.

Session mode mirrors lock/leak/keywitness: DRUID_TPU_STALL_WITNESS=1
installs a process-wide singleton from tests/conftest.py (BEFORE
druid_tpu imports, so `from time import sleep`-style early bindings
cannot escape it) and fails the run on any untimed non-shutdown park in
pytest_unconfigure. The chaos harness's dead/slow/hang scenarios are
the stress leg: a wedged peer must produce bounded, timed parks only.

Test-only: nothing in druid_tpu imports this module.
"""
from __future__ import annotations

import os
import queue
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: process-wide session witness (see session_witness)
_SESSION: Optional["StallWitness"] = None


def session_witness(root: Optional[str] = None,
                    prefixes: Sequence[str] = ("druid_tpu",)) \
        -> Optional["StallWitness"]:
    """Install (once) and return the process-wide witness; with root=None
    just return whatever is installed."""
    global _SESSION
    if _SESSION is None and root is not None:
        _SESSION = StallWitness(root, prefixes).install()
    return _SESSION


def end_session_witness() -> Optional["StallWitness"]:
    """Uninstall and return the session witness (None if never armed)."""
    global _SESSION
    w, _SESSION = _SESSION, None
    if w is not None:
        w.uninstall()
    return w


#: a frame with one of these co_names anywhere up-stack marks the park as
#: shutdown-scoped: an untimed park is a deliberate drain, not a stall
_SHUTDOWN_SCOPES = frozenset({
    "stop", "close", "shutdown", "terminate", "cleanup", "uninstall",
    "__exit__", "__del__", "atexit_handler", "_await_proc",
    "stop_server", "join_all", "drain", "pytest_unconfigure",
    "end_session_witness",
})

#: how far up the stack the shutdown-scope probe walks; teardown entries
#: sit near the top of test/fixture stacks, but 25 frames covers every
#: real chain in the suite without paying a full stack unwind per park
_SCOPE_PROBE_DEPTH = 25

Site = Tuple[str, int, str]              # (rel_path, line, primitive)


def _timeout_pos(pos: int):
    """Timeout extractor for a bound method whose timeout is positional
    argument `pos` (self included) or the `timeout` keyword."""
    def of(args, kwargs):
        t = args[pos] if len(args) > pos else kwargs.get("timeout")
        return t is not None
    return of


def _queue_get_timed(args, kwargs):
    block = args[1] if len(args) > 1 else kwargs.get("block", True)
    if block is False:
        return True                      # non-blocking get cannot park
    t = args[2] if len(args) > 2 else kwargs.get("timeout")
    return t is not None


class StallWitness:
    """Times real parks at druid_tpu call sites; untimed parks outside a
    shutdown scope are violations."""

    def __init__(self, root: str, prefixes: Sequence[str] = ("druid_tpu",)):
        self.root = os.path.abspath(root)
        self.prefixes = tuple(prefixes)
        self._lock = threading.Lock()
        #: site -> {"count", "untimed", "max_s"}
        self.sites: Dict[Site, Dict[str, float]] = {}
        self.violations: List[str] = []
        self._saved: List[Tuple[object, str, object]] = []
        self._installed = False

    # -- eligibility (lockwitness's one rule) ------------------------------

    def _rel_under_prefixes(self, path: str) -> Optional[str]:
        path = os.path.abspath(path)
        if not path.startswith(self.root + os.sep):
            return None
        rel = os.path.relpath(path, self.root).replace(os.sep, "/")
        if not any(rel.startswith(p.rstrip("/") + "/") or rel == p
                   for p in self.prefixes):
            return None
        return rel

    @staticmethod
    def _shutdown_scoped(frame) -> bool:
        f, depth = frame, 0
        while f is not None and depth < _SCOPE_PROBE_DEPTH:
            if f.f_code.co_name in _SHUTDOWN_SCOPES:
                return True
            f = f.f_back
            depth += 1
        return False

    # -- ledger ------------------------------------------------------------

    def _record(self, site: Site, timed: bool, dur_s: float,
                shutdown: bool, thread_name: str) -> None:
        with self._lock:
            st = self.sites.setdefault(
                site, {"count": 0, "untimed": 0, "max_s": 0.0})
            st["count"] += 1
            st["max_s"] = max(st["max_s"], dur_s)
            if not timed:
                st["untimed"] += 1
                if not shutdown:
                    self.violations.append(
                        f"{site[0]}:{site[1]}: untimed {site[2]} park on "
                        f"thread {thread_name!r} outside any shutdown "
                        f"scope (parked {dur_s:.3f}s this time; nothing "
                        f"bounds the next one)")

    # -- install/uninstall -------------------------------------------------

    def install(self) -> "StallWitness":
        if self._installed:
            return self
        witness = self

        def wrap(owner, attr, kind, timed_of):
            real = getattr(owner, attr)

            def wrapped(*args, **kwargs):
                f = sys._getframe(1)
                rel = witness._rel_under_prefixes(f.f_code.co_filename)
                if rel is None:
                    return real(*args, **kwargs)
                site = (rel, f.f_lineno, kind)
                timed = timed_of(args, kwargs)
                shutdown = witness._shutdown_scoped(f)
                t0 = time.monotonic()
                try:
                    return real(*args, **kwargs)
                finally:
                    witness._record(site, timed,
                                    time.monotonic() - t0, shutdown,
                                    threading.current_thread().name)

            wrapped.__name__ = getattr(real, "__name__", attr)
            witness._saved.append((owner, attr, real))
            setattr(owner, attr, wrapped)

        always = lambda args, kwargs: True  # noqa: E731
        wrap(threading.Event, "wait", "event-wait", _timeout_pos(1))
        wrap(threading.Condition, "wait", "cond-wait", _timeout_pos(1))
        wrap(threading.Thread, "join", "thread-join", _timeout_pos(1))
        wrap(queue.Queue, "get", "queue-get", _queue_get_timed)
        wrap(subprocess.Popen, "wait", "proc-wait", _timeout_pos(1))
        wrap(time, "sleep", "sleep", always)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for obj, attr, original in reversed(self._saved):
            setattr(obj, attr, original)
        self._saved.clear()
        self._installed = False

    def __enter__(self) -> "StallWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- reporting ---------------------------------------------------------

    def max_park_s(self) -> float:
        with self._lock:
            return max((st["max_s"] for st in self.sites.values()),
                       default=0.0)

    def summary(self) -> str:
        with self._lock:
            n_sites = len(self.sites)
            n_parks = sum(int(st["count"]) for st in self.sites.values())
            untimed = sum(int(st["untimed"]) for st in self.sites.values())
            longest = max(self.sites.items(),
                          key=lambda kv: kv[1]["max_s"], default=None)
        out = (f"stall witness: {n_parks} park(s) at {n_sites} site(s), "
               f"{untimed} untimed (shutdown-scoped or flagged), "
               f"{len(self.violations)} violation(s)")
        if longest is not None:
            (rel, line, kind), st = longest
            out += (f"; longest {st['max_s']:.3f}s "
                    f"({kind} at {rel}:{line})")
        return out
