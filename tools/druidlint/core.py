"""druidlint core: rule registry, config, suppressions, baseline, runner.

Design notes:
  * Findings key on (rule, path, line) — the same identity scheme the
    baseline file uses, so `--fail-on-new` is a set difference.
  * Suppression is per physical line: a `# druidlint: disable=<rule>[,..]`
    comment on the line a finding anchors to silences it (`disable=all`
    silences every rule on that line). Suppressions are for invariant-
    preserving exceptions the rule cannot see (e.g. an availability probe
    that must never raise); anything else belongs in the baseline or gets
    fixed.
  * Config comes from pyproject.toml [tool.druidlint]; the container's
    Python (3.10) predates tomllib, so a minimal single-table parser
    handles the subset this project writes (strings, string arrays, ints,
    bools). Unknown keys are rejected loudly — a typoed option silently
    disabling a rule would defeat the gate.
"""
from __future__ import annotations

import ast
import fnmatch
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set

SEVERITIES = ("error", "warning")

_SUPPRESS_RE = re.compile(r"#\s*druidlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    col: int
    message: str
    severity: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.line}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.severity}] {self.rule}: {self.message}")

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclass
class Rule:
    name: str
    severity: str
    description: str
    check: Callable[["ModuleContext"], Iterable[Finding]]


_RULES: Dict[str, Rule] = {}


def rule(name: str, severity: str, description: str):
    """Register a rule. The decorated function receives a ModuleContext and
    yields Findings (built via ctx.finding)."""
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r} for rule {name!r}")

    def deco(fn):
        _RULES[name] = Rule(name, severity, fn.__doc__ or description, fn)
        return fn
    return deco


def registered_rules() -> Dict[str, Rule]:
    from tools.druidlint import rules as _rules  # noqa: F401 (registration)
    from tools.druidlint import tracecheck as _tracecheck  # noqa: F401
    from tools.druidlint import raceguard as _raceguard  # noqa: F401
    from tools.druidlint import leakguard as _leakguard  # noqa: F401
    from tools.druidlint import keyguard as _keyguard  # noqa: F401
    from tools.druidlint import stallguard as _stallguard  # noqa: F401
    from tools.druidlint import donorguard as _donorguard  # noqa: F401
    return dict(_RULES)


#: analyzer family of a rule, derived from the registering module — the
#: unified `--all` runner groups findings and timings by this
_FAMILIES = {"rules": "druidlint", "tracecheck": "tracecheck",
             "raceguard": "raceguard", "leakguard": "leakguard",
             "keyguard": "keyguard", "stallguard": "stallguard",
             "donorguard": "donorguard"}


def family_of(r: Rule) -> str:
    mod = getattr(r.check, "__module__", "") or ""
    return _FAMILIES.get(mod.rsplit(".", 1)[-1], "druidlint")


# ---- configuration -------------------------------------------------------

_DEFAULT_CONFIG = {
    "include": ["druid_tpu", "tools", "bench.py", "__graft_entry__.py"],
    "exclude": ["**/__pycache__/**", "*.pyc"],
    "rules": [],                        # empty = all registered rules
    "baseline": "tools/druidlint/baseline.json",
    # unfenced-metadata-write: leader-duty modules whose MetadataStore
    # mutations must thread a fencing term
    "duty-modules": ["druid_tpu/cluster/coordinator.py",
                     "druid_tpu/indexing/overlord.py"],
    # no-executable-deserialization + wire-decoded-rows: modules that face
    # the wire / carry the compressed data path end to end
    "wire-modules": ["druid_tpu/cluster/wire.py",
                     "druid_tpu/cluster/cache.py",
                     "druid_tpu/server/*",
                     "druid_tpu/storage/format_v2.py"],
    # host-device-sync: modules whose traced functions are device code
    "device-modules": ["druid_tpu/engine/*", "druid_tpu/parallel/*"],
    # lock-scope: modules exempted because the lock EXISTS to serialize the
    # blocking resource (metadata.py's lock guards its one sqlite conn)
    "lock-scope-exclude": ["druid_tpu/cluster/metadata.py"],
    # tracecheck: modules holding pallas kernels (tile/accum/vmem rules)
    "pallas-modules": ["druid_tpu/engine/pallas_agg.py",
                       "druid_tpu/engine/megakernel.py"],
    # tracecheck: modules defining AggKernel subclasses (agg-contract)
    "kernel-modules": ["druid_tpu/engine/kernels.py", "druid_tpu/ext/*"],
    # tracecheck: the canonical sharding-layout module(s) — shard_map
    # partition specs are checked against mesh construction + body arity
    # (shard-spec) there, and PartitionSpec/NamedSharding literals
    # anywhere ELSE are findings (spec-literal-outside-layout)
    "shard-modules": ["druid_tpu/parallel/speclayout.py"],
    # tracecheck: VMEM tile budget in bytes; 0 = contracts.VMEM_BUDGET_BYTES
    "vmem-cap-bytes": 0,
    # unbounded-retry: data-plane modules whose catch-and-retry loops
    # must consult a Deadline or attempt bound
    "retry-modules": ["druid_tpu/cluster/*", "druid_tpu/server/*"],
    # raceguard: the whole-program concurrency-analysis member set — every
    # module whose locks/threads/shared state enter the shared index
    "raceguard-modules": ["druid_tpu/*"],
    # raceguard: thread roots the AST cannot see, as "path-glob::qual-glob"
    # (e.g. "druid_tpu/*::*.do_monitor" — monitor ticks run on the
    # MonitorScheduler thread but are dispatched through a list the binder
    # cannot type)
    "extra-thread-roots": [],
    # raceguard: declared order edges ("lockid -> lockid") for acquisition
    # paths through OPAQUE callbacks the binder cannot enumerate (a
    # handoff lambda announcing to the view under the driver lock); they
    # join the static order graph, so they participate in cycle detection
    # and explain dynamic-witness observations
    "raceguard-assume-edges": [],
    # metric-name: modules whose emitter.metric("...") literals must be
    # declared in the metrics catalog
    "metric-modules": ["druid_tpu/*"],
    # metric-name: the single-source metrics catalog (METRICS dict literal)
    "metrics-catalog": "druid_tpu/obs/catalog.py",
    # flag-name: modules whose literal DRUID_TPU_* env reads must name a
    # flag declared in the flags catalog
    "flag-modules": ["druid_tpu/*"],
    # flag-name + keyguard env-flag-latch: the single-source flags
    # catalog (FLAGS dict literal of Flag(...) declarations)
    "flags-catalog": "druid_tpu/config/flags.py",
    # keyguard env-flag-latch: plan/build modules where a DRUID_TPU_*
    # read must match its declared latch/live semantics
    "keyguard-plan-modules": ["druid_tpu/engine/*", "druid_tpu/data/*",
                              "druid_tpu/parallel/*"],
    # keyguard unkeyed-trace-input: canonical key-derivation functions
    # ("path::qual"); every parameter must flow into the returned key
    "keyguard-key-fns": ["druid_tpu/engine/grouping.py::_structure_sig",
                         "druid_tpu/parallel/distributed.py::_sharded_sig",
                         "druid_tpu/parallel/speclayout.py::layout_sig",
                         "druid_tpu/engine/filters.py::bitmap_pool_key",
                         "druid_tpu/cluster/cache.py::query_cache_key",
                         "druid_tpu/cluster/cache.py::result_level_key",
                         "druid_tpu/data/cascade.py::plan_pair"],
    # keyguard impure-eligibility: eligibility/planning predicates
    # ("path::qual") that must stay pure functions of descriptors
    "keyguard-eligibility": ["druid_tpu/engine/standing.py::check_eligible",
                             "druid_tpu/data/cascade.py::plan_columns",
                             "druid_tpu/data/cascade.py::plan_pair",
                             "druid_tpu/data/cascade.py::run_domain_probe",
                             "druid_tpu/data/packed.py::plan_columns",
                             "druid_tpu/cluster/view.py::*.fusable"],
    # stallguard: request-path entry points the handler heuristic cannot
    # see, as "path-glob::qual-glob" — functions that run ON a request
    # thread (the long-poll hub entry, the scheduler admission gate);
    # everything they reach through the call graph inherits the
    # request-path park rules
    "stallguard-request-roots": [],
    # donorguard donate-platform-gate: the blessed platform predicates
    # ("path-glob::qual-glob") — the ONE donation gate plus the pallas
    # availability probe; a backend/platform comparison anywhere else is
    # a scattered donation-enable decision (the CPU-segfault class)
    "donorguard-platform-gate": [
        "druid_tpu/engine/contracts.py::donation_supported",
        "druid_tpu/engine/pallas_agg.py::backend_ok"],
    # unused-suppression audit (CLI --report-unused-suppressions)
    "report-unused-suppressions": False,
}


@dataclass
class LintConfig:
    include: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["include"]))
    exclude: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["exclude"]))
    rules: List[str] = field(default_factory=list)
    baseline: str = _DEFAULT_CONFIG["baseline"]
    duty_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["duty-modules"]))
    wire_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["wire-modules"]))
    device_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["device-modules"]))
    lock_scope_exclude: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["lock-scope-exclude"]))
    pallas_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["pallas-modules"]))
    kernel_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["kernel-modules"]))
    shard_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["shard-modules"]))
    vmem_cap_bytes: int = 0
    retry_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["retry-modules"]))
    raceguard_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["raceguard-modules"]))
    extra_thread_roots: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["extra-thread-roots"]))
    raceguard_assume_edges: List[str] = field(
        default_factory=lambda: list(
            _DEFAULT_CONFIG["raceguard-assume-edges"]))
    metric_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["metric-modules"]))
    metrics_catalog: str = _DEFAULT_CONFIG["metrics-catalog"]
    flag_modules: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["flag-modules"]))
    flags_catalog: str = _DEFAULT_CONFIG["flags-catalog"]
    keyguard_plan_modules: List[str] = field(
        default_factory=lambda: list(
            _DEFAULT_CONFIG["keyguard-plan-modules"]))
    keyguard_key_fns: List[str] = field(
        default_factory=lambda: list(_DEFAULT_CONFIG["keyguard-key-fns"]))
    keyguard_eligibility: List[str] = field(
        default_factory=lambda: list(
            _DEFAULT_CONFIG["keyguard-eligibility"]))
    stallguard_request_roots: List[str] = field(
        default_factory=lambda: list(
            _DEFAULT_CONFIG["stallguard-request-roots"]))
    donorguard_platform_gate: List[str] = field(
        default_factory=lambda: list(
            _DEFAULT_CONFIG["donorguard-platform-gate"]))
    report_unused_suppressions: bool = False
    #: scan root; tracecheck resolves druid_tpu/engine/contracts.py here
    #: (set by load_config/lint_paths, not a pyproject key)
    root: str = "."

    def enabled_rules(self) -> Dict[str, Rule]:
        all_rules = registered_rules()
        if not self.rules:
            return all_rules
        unknown = set(self.rules) - set(all_rules)
        if unknown:
            raise ValueError(f"unknown rules in config: {sorted(unknown)}")
        return {n: r for n, r in all_rules.items() if n in self.rules}


def _parse_toml_value(raw: str):
    raw = raw.strip()
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return ast.literal_eval(raw)
    except (ValueError, SyntaxError):
        raise ValueError(f"unsupported TOML value for druidlint: {raw!r}")


def _read_druidlint_table(pyproject: Path) -> Dict[str, object]:
    """Minimal parser for the [tool.druidlint] table (no tomllib on 3.10):
    key = <string | int | bool | [string, ...]>, arrays may span lines."""
    out: Dict[str, object] = {}
    if not pyproject.exists():
        return out
    in_table = False
    pending_key, pending_val = None, ""
    header = re.compile(r"^\[([^\]]+)\]\s*(#.*)?$")
    for line in pyproject.read_text().splitlines():
        stripped = line.strip()
        m = header.match(stripped)
        if m:
            in_table = m.group(1).strip() == "tool.druidlint"
            continue
        if not in_table or not stripped or stripped.startswith("#"):
            continue
        if pending_key is not None:
            pending_val += " " + stripped
            if stripped.endswith("]"):
                out[pending_key] = _parse_toml_value(pending_val)
                pending_key, pending_val = None, ""
            continue
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if val.startswith("[") and not val.endswith("]"):
            pending_key, pending_val = key, val
            continue
        out[key] = _parse_toml_value(val)
    if pending_key is not None:
        raise ValueError(f"unterminated array for [tool.druidlint] "
                         f"key {pending_key!r}")
    return out


def load_config(root: Path) -> LintConfig:
    table = _read_druidlint_table(root / "pyproject.toml")
    cfg = LintConfig()
    known = {k.replace("_", "-") for k in vars(cfg)} - {"root"}
    unknown = set(table) - known
    if unknown:
        raise ValueError(f"unknown [tool.druidlint] keys: {sorted(unknown)}")
    for key, val in table.items():
        setattr(cfg, key.replace("-", "_"), val)
    cfg.root = str(root)
    return cfg


# ---- per-module context ---------------------------------------------------

class ModuleContext:
    """Everything a rule needs about one module: path, AST (with parent
    links), source lines, config."""

    def __init__(self, path: str, source: str, config: LintConfig):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.config = config
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self._rule: Optional[Rule] = None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parent(cur)
        return None

    def path_matches(self, patterns: List[str]) -> bool:
        return any(fnmatch.fnmatch(self.path, pat) or self.path == pat
                   for pat in patterns)

    def finding(self, node: ast.AST, message: str) -> Finding:
        assert self._rule is not None
        return Finding(self._rule.name, self.path,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0) + 1,
                       message, self._rule.severity)


def _suppressions(lines: List[str]) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def check_source(source: str, path: str,
                 config: Optional[LintConfig] = None) -> List[Finding]:
    """Lint one module given as a string — the unit-test entry point."""
    config = config or LintConfig()
    ctx = ModuleContext(path, source, config)
    suppressed = _suppressions(ctx.lines)
    used: Set[tuple] = set()            # (line, rule-or-"all") that matched
    findings: List[Finding] = []
    enabled = config.enabled_rules()
    for r in enabled.values():
        ctx._rule = r
        for f in r.check(ctx):
            lines_rules = suppressed.get(f.line, ())
            if "all" in lines_rules:
                used.add((f.line, "all"))
                continue
            if f.rule in lines_rules:
                used.add((f.line, f.rule))
                continue
            findings.append(f)
    if config.report_unused_suppressions and "unused-suppression" in enabled:
        sev = enabled["unused-suppression"].severity
        all_rules = set(registered_rules())
        for line, names in sorted(suppressed.items()):
            if "unused-suppression" in names:
                continue            # the audit's own pragma silences it
            for name in sorted(names):
                if (line, name) in used:
                    continue
                if name == "all":
                    # only auditable when every rule ran this pass
                    if config.rules:
                        continue
                    msg = ("disable=all suppresses no finding on this "
                           "line — remove the dead pragma")
                elif name not in all_rules:
                    msg = (f"disable={name} names no registered rule — "
                           f"a typoed pragma suppresses nothing")
                elif name not in enabled:
                    continue        # rule not run: usage unknowable
                else:
                    msg = (f"disable={name} suppresses no finding on "
                           f"this line — remove the dead pragma")
                findings.append(Finding("unused-suppression", path, line,
                                        1, msg, sev))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---- file collection + runner --------------------------------------------

def _excluded(rel: str, config: LintConfig) -> bool:
    return any(fnmatch.fnmatch(rel, pat) for pat in config.exclude)


def collect_files(root: Path, config: LintConfig,
                  paths: Optional[List[str]] = None) -> List[Path]:
    roots = paths if paths else config.include
    out: List[Path] = []
    seen: Set[Path] = set()
    for entry in roots:
        p = (root / entry) if not Path(entry).is_absolute() else Path(entry)
        if p.is_dir():
            candidates: Iterator[Path] = sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            candidates = iter([p])
        else:
            continue
        for c in candidates:
            try:
                rel = c.relative_to(root).as_posix()
            except ValueError:
                # outside the root (scratch file): rules keyed on repo
                # paths simply won't match it
                rel = c.as_posix()
            if c in seen or _excluded(rel, config):
                continue
            seen.add(c)
            out.append(c)
    return out


def _cache_meta_sig(root: Path, config: LintConfig) -> str:
    """Identity of everything findings depend on besides the scanned file:
    the analyzer sources (rules + core + tracecheck), the engine contracts
    module, the effective config — and the raceguard PROGRAM signature
    (every member module's mtime/size): raceguard findings in module B can
    change when module A changes, so any edit inside the program set must
    drop every per-file cache entry, not just the edited file's."""
    from tools.druidlint.tracecheck import contracts_path  # lazy: no cycle
    from tools.druidlint.raceguard import program_sig  # lazy: no cycle
    # private attrs are per-run caches (raceguard memoizes its program on
    # the config), not finding-relevant identity
    parts = [repr(sorted((k, v) for k, v in vars(config).items()
                         if not k.startswith("_"))),
             program_sig(root, config)]
    tool_files = sorted(Path(__file__).parent.glob("*.py"))
    contracts = contracts_path(str(root))
    if contracts is not None:
        tool_files.append(contracts)
    for p in tool_files:
        try:
            st = p.stat()
            parts.append(f"{p.name}:{st.st_mtime_ns}:{st.st_size}")
        except OSError:
            parts.append(f"{p.name}:gone")
    return "|".join(parts)


def _finding_from_cache(entry: dict) -> Finding:
    return Finding(entry["rule"], entry["path"], entry["line"],
                   entry["col"], entry["message"], entry["severity"])


def _finding_to_cache(f: Finding) -> dict:
    return {"rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
            "message": f.message, "severity": f.severity}


def lint_paths(root: Path, config: Optional[LintConfig] = None,
               paths: Optional[List[str]] = None,
               cache_path: Optional[Path] = None) -> List[Finding]:
    """Lint the tree. With `cache_path`, per-file findings are reused when
    the file's (mtime, size) and the analyzer/config identity are unchanged
    — the full-tree scan stays inside the tier-1 time budget even with the
    symbolic-shape rules enabled. Rules are strictly per-module, so file
    identity is a sound cache key."""
    config = config or load_config(root)
    config.root = str(root)
    cache: Dict[str, dict] = {}
    meta_sig = None
    if cache_path is not None:
        meta_sig = _cache_meta_sig(root, config)
        try:
            data = json.loads(cache_path.read_text())
            if data.get("version") == 1 and data.get("meta") == meta_sig:
                cache = data.get("files", {})
        except (OSError, ValueError):
            cache = {}
    out_files: Dict[str, dict] = {}
    findings: List[Finding] = []
    for f in collect_files(root, config, paths):
        try:
            rel = f.relative_to(root).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            st = f.stat()
            key = f"{st.st_mtime_ns}:{st.st_size}"
        except OSError:
            key = "gone"
        hit = cache.get(rel)
        if hit is not None and hit.get("key") == key:
            file_findings = [_finding_from_cache(e)
                             for e in hit["findings"]]
            findings.extend(file_findings)
            out_files[rel] = hit
            continue
        try:
            source = f.read_text()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            file_findings = check_source(source, rel, config)
        except SyntaxError as e:
            file_findings = [Finding("syntax-error", rel, e.lineno or 1,
                                     (e.offset or 0) + 1, str(e.msg),
                                     "error")]
        findings.extend(file_findings)
        out_files[rel] = {"key": key,
                          "findings": [_finding_to_cache(x)
                                       for x in file_findings]}
    if cache_path is not None:
        # merge over the loaded cache: a restricted-path scan must not
        # truncate the full tree's entries (stale files re-key on read;
        # deleted files linger harmlessly until the next meta change)
        cache.update(out_files)
        try:
            cache_path.write_text(json.dumps(
                {"version": 1, "meta": meta_sig, "files": cache}))
        except OSError:
            pass                      # cache is best-effort, never fatal
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---- baseline -------------------------------------------------------------

def load_baseline(path: Path) -> Dict[str, dict]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    out = {}
    for entry in data.get("findings", []):
        key = f"{entry['rule']}:{entry['path']}:{entry['line']}"
        out[key] = entry
    return out


def save_baseline(path: Path, findings: List[Finding]) -> None:
    data = {"version": 1,
            "findings": [f.to_json() for f in findings]}
    path.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")


def split_by_baseline(findings: List[Finding], baseline: Dict[str, dict]):
    """Returns (new, grandfathered, stale-baseline-keys)."""
    new = [f for f in findings if f.key not in baseline]
    old = [f for f in findings if f.key in baseline]
    stale = sorted(set(baseline) - {f.key for f in findings})
    return new, old, stale
