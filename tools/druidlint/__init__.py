"""druidlint — project-invariant static analysis for druid-tpu.

An AST-based analyzer (stdlib only) that mechanically enforces the
invariants the codebase otherwise holds by convention: fenced control-plane
writes, retrace-free engine hot paths, no executable deserialization on the
wire, no silently swallowed exceptions, and no blocking work under locks.

Usage:
    python -m tools.druidlint [--fail-on-new] [paths...]

Control-plane rules live in rules.py; the engine-layer shape/dtype/VMEM
contract rules (abstract interpretation against
druid_tpu/engine/contracts.py) in tracecheck.py; configuration in
pyproject.toml [tool.druidlint]; grandfathered findings in baseline.json.
See README "Static analysis".
"""
from tools.druidlint.core import (Finding, LintConfig, check_source,
                                  lint_paths, load_baseline, load_config,
                                  registered_rules)

__all__ = ["Finding", "LintConfig", "check_source", "lint_paths",
           "load_baseline", "load_config", "registered_rules"]
