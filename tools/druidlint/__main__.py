"""druidlint CLI.

    python -m tools.druidlint                    # report every finding
    python -m tools.druidlint --fail-on-new      # tier-1 gate: only
                                                 # non-baselined findings fail
    python -m tools.druidlint --update-baseline  # grandfather current state
    python -m tools.druidlint --list-rules
    python -m tools.druidlint druid_tpu/engine   # restrict scan paths
    python -m tools.druidlint --changed          # pre-commit: scan only
                                                 # git-modified modules
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

from tools.druidlint.core import (family_of, lint_paths, load_baseline,
                                  load_config, registered_rules,
                                  save_baseline, split_by_baseline)

#: the seven analyzer families --all asserts are all registered and runs
#: in ONE process over ONE shared program/cache pass (tier-1 used to pay
#: the whole-program index once per analyzer CLI invocation)
_ALL_FAMILIES = ("druidlint", "tracecheck", "raceguard", "leakguard",
                 "keyguard", "stallguard", "donorguard")


def _changed_paths(root: Path):
    """Repo-relative paths touched since HEAD (worktree modifications plus
    untracked files), or None when git cannot answer — the caller falls
    back to a full scan rather than silently under-scanning."""
    out = []
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.extend(l.strip() for l in proc.stdout.splitlines() if l.strip())
    return sorted(set(out))


def _scan_scope_for_changed(root: Path, config) -> object:
    """Scan scope for --changed: a list of changed member .py files, or
    None meaning FULL scan. Full scan happens when git is unavailable or
    when the cache's meta signature went stale (analyzer sources, config,
    or any program-set module changed): whole-program families can grow
    findings in UNCHANGED modules then, so a diff-scoped scan would lie."""
    changed = _changed_paths(root)
    if changed is None:
        return None
    from tools.druidlint.core import _cache_meta_sig
    cache_file = root / ".druidlint-cache.json"
    try:
        meta = json.loads(cache_file.read_text()).get("meta")
    except (OSError, ValueError):
        meta = None
    if meta != _cache_meta_sig(root, config):
        return None
    include = [p.rstrip("/") for p in config.include]
    scope = [p for p in changed
             if p.endswith(".py") and (root / p).exists()
             and any(p == inc or p.startswith(inc + "/")
                     for inc in include)]
    return scope


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="druidlint")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: config include list)")
    ap.add_argument("--root", default=".",
                    help="repo root (pyproject.toml + baseline live here)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: config)")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="fail only on findings absent from the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current findings as the new baseline")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--only", default=None, metavar="RULE[,RULE...]",
                    help="run only these rules (comma-separated)")
    ap.add_argument("--report-unused-suppressions", action="store_true",
                    help="also report disable pragmas that suppress nothing")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the mtime-keyed per-file scan cache")
    ap.add_argument("--dot", action="store_true",
                    help="print the raceguard lock-order graph as graphviz "
                         "DOT (cycle members red) and exit")
    ap.add_argument("--changed", action="store_true",
                    help="pre-commit mode: scan only modules touched since "
                         "HEAD (git diff + untracked). Falls back to a "
                         "FULL scan when git is unavailable or the shared "
                         "program index changed (whole-program findings "
                         "can move across modules then)")
    ap.add_argument("--all", action="store_true", dest="all_families",
                    help="unified gate: assert all seven analyzer families "
                         "(druidlint/tracecheck/raceguard/leakguard/"
                         "keyguard/stallguard/donorguard) are registered, "
                         "run them in one process over the shared caches, "
                         "and report findings per family")
    args = ap.parse_args(argv)

    if args.all_families and args.only:
        print("druidlint: --all runs every family; it cannot be combined "
              "with --only", file=sys.stderr)
        return 2

    if args.update_baseline and (args.paths or args.only or args.changed):
        # a partial scan (by path OR by rule subset) would overwrite — and
        # so silently drop — every grandfathered finding it didn't re-find
        print("druidlint: --update-baseline requires a full scan — do not "
              "pass explicit paths, --only, or --changed with it",
              file=sys.stderr)
        return 2

    if args.changed and args.paths:
        print("druidlint: --changed derives its own scan scope from git; "
              "it cannot be combined with explicit paths", file=sys.stderr)
        return 2

    if args.list_rules:
        for name, r in sorted(registered_rules().items()):
            doc = (r.check.__doc__ or r.description).strip().split("\n")[0]
            print(f"{name} [{r.severity}]: {doc}")
        return 0

    root = Path(args.root).resolve()
    try:
        config = load_config(root)
    except ValueError as e:
        print(f"druidlint: config error: {e}", file=sys.stderr)
        return 2
    if args.only:
        config.rules = [r.strip() for r in args.only.split(",") if r.strip()]
        unknown = set(config.rules) - set(registered_rules())
        if unknown:
            print(f"druidlint: unknown rules in --only: {sorted(unknown)}",
                  file=sys.stderr)
            return 2
    if args.report_unused_suppressions:
        config.report_unused_suppressions = True
    if args.all_families:
        # a family that fails to import/register would otherwise degrade
        # the gate silently — the unified runner makes absence an error
        present = {family_of(r) for r in registered_rules().values()}
        missing = [f for f in _ALL_FAMILIES if f not in present]
        if missing:
            print(f"druidlint: --all: analyzer famil(ies) missing from the "
                  f"registry: {missing}", file=sys.stderr)
            return 2
    if args.dot:
        from tools.druidlint.raceguard import analyze_tree, render_dot
        print(render_dot(analyze_tree(root, config)), end="")
        return 0
    baseline_path = Path(args.baseline) if args.baseline \
        else root / config.baseline
    cache_path = None if args.no_cache else root / ".druidlint-cache.json"

    scan_paths = args.paths or None
    changed_scope = None
    if args.changed:
        changed_scope = _scan_scope_for_changed(root, config)
        if changed_scope is not None:
            scan_paths = changed_scope

    t0 = time.monotonic()
    if changed_scope == []:
        findings = []                 # nothing touched: nothing to scan
    else:
        try:
            findings = lint_paths(root, config, scan_paths,
                                  cache_path=cache_path)
        except ValueError as e:
            print(f"druidlint: {e}", file=sys.stderr)
            return 2
    elapsed = time.monotonic() - t0
    if args.changed and not args.as_json:
        if changed_scope is None:
            print("druidlint: --changed: full scan (git unavailable or "
                  "the shared program index changed)")
        else:
            print(f"druidlint: --changed: {len(changed_scope)} touched "
                  f"module(s) in scope")

    if args.update_baseline:
        save_baseline(baseline_path, findings)
        print(f"druidlint: baseline updated with {len(findings)} "
              f"finding(s) at {baseline_path}")
        return 0

    if args.fail_on_new:
        baseline = load_baseline(baseline_path)
        new, old, stale = split_by_baseline(findings, baseline)
        report = new
    else:
        new, old, stale = findings, [], []
        report = findings

    rules = registered_rules()

    def fam(f):
        r = rules.get(f.rule)
        return family_of(r) if r is not None else "druidlint"

    counts = {name: 0 for name in _ALL_FAMILIES}
    if args.all_families:
        for f in report:
            counts[fam(f)] = counts.get(fam(f), 0) + 1

    if args.as_json:
        payload = {"findings": [f.to_json() | {"col": f.col,
                                               "severity": f.severity}
                                | ({"family": fam(f)}
                                   if args.all_families else {})
                                for f in report],
                   "grandfathered": len(old),
                   "stale_baseline": stale}
        if args.all_families:
            payload["families"] = {
                name: {"rules": sum(1 for r in rules.values()
                                    if family_of(r) == name),
                       "findings": counts.get(name, 0)}
                for name in _ALL_FAMILIES}
            payload["elapsed_s"] = round(elapsed, 3)
        print(json.dumps(payload, indent=2))
    else:
        for f in report:
            print(f.format())
        for key in stale:
            print(f"druidlint: note: baseline entry no longer fires "
                  f"(remove it): {key}")
        label = "new finding(s)" if args.fail_on_new else "finding(s)"
        if args.all_families:
            per_family = ", ".join(f"{name} {counts.get(name, 0)}"
                                   for name in _ALL_FAMILIES)
            print(f"druidlint --all: {per_family} {label}; {len(old)} "
                  f"grandfathered, {len(stale)} stale baseline entr(ies) "
                  f"in {elapsed:.2f}s (one shared program pass)")
        else:
            print(f"druidlint: {len(report)} {label}, {len(old)} "
                  f"grandfathered, {len(stale)} stale baseline entr(ies) "
                  f"in {elapsed:.2f}s")
    return 1 if report else 0


if __name__ == "__main__":
    sys.exit(main())
