"""raceguard: whole-program lock / thread-root concurrency analysis.

The existing druidlint rules are strictly per-module; concurrency bugs are
not. A broker pool thread racing a duty loop into an unlocked dict lives in
the SPACE BETWEEN modules: the write is innocent where it stands — it is
only wrong because some other file spawned a thread that can reach it. So
raceguard builds one program-level index over every module matching config
`raceguard-modules` (default: all of druid_tpu/) and derives:

  * lock objects — `self._lock = threading.Lock()` instance locks (identity:
    class + attribute, one id per class, NOT per instance), module-level
    locks, and `threading.Condition(self._lock)` aliases (a condition built
    on a lock IS that lock);
  * guarded state — attributes/globals written while a lock is held;
  * thread roots — Thread(target=...)/Timer, executor .submit/.map,
    weakref.finalize callbacks, BaseHTTPRequestHandler do_* methods, plus
    config `extra-thread-roots` patterns ("druid_tpu/*::*.do_monitor" marks
    every monitor tick a root);
  * a call graph with a light type binder — constructor calls
    (`self._pool = DevicePool(...)`), annotated parameters (inherited from
    overridden base methods), `Dict[K, V]` element annotations, return
    annotations, `outer = self` closures (the nested HTTP-handler idiom),
    @property loads, callable instances (`self.clock()` →
    `ManualClock.__call__`), constructor ARGUMENTS typing the attributes
    the params land in, dynamic dispatch to subclass overrides, and lambda
    callbacks invoked by their receiver (`critical_section(id, lambda:
    metadata.publish(...))` runs the publish under the box lock). Config
    `raceguard-assume-edges` declares order edges for contracts even that
    cannot see (opaque handoff callbacks); declared edges join the cycle
    check. Two lock-set dataflows run over the graph:
      - MUST-held (intersection over call sites): precision for the guard
        rules — `_evict_to` called only under the lock is correctly treated
        as locked;
      - MAY-held (union): completeness for the lock-order graph — the
        dynamic witness (tools/druidlint/lockwitness.py) asserts every
        acquisition order OBSERVED at runtime is an edge this graph
        predicted, so MAY must over-approximate.

Four rules ride the shared druidlint registry/baseline/suppression/cache
machinery (suppress with `# druidlint: disable=<rule>  # <rationale>` on
the flagged line):

  unguarded-shared-write  an attribute written both under a lock and
                          outside it, or written from ≥2 concurrent thread
                          roots with no common lock;
  lock-order-cycle        a cycle in the static lock-acquisition-order
                          graph (ABBA deadlock potential), plus same-lock
                          self-deadlock through a self-call chain on a
                          non-reentrant Lock;
  lock-in-traced          a lock acquired inside jitted/shard_map/pallas
                          code — trace-time it runs once (a silent no-op as
                          a guard), and a captured lock in a compiled
                          callable deadlocks under re-entry;
  guard-consistency       a read of a consistently-guarded attribute on a
                          thread-root path without its lock.

Whole-program soundness vs the per-file mtime cache: a change in module A
can change findings in module B, so core._cache_meta_sig folds program_sig()
(every raceguard module's mtime/size) into the cache identity — any edit
under druid_tpu/ drops the whole cache rather than serving stale
cross-module findings.
"""
from __future__ import annotations

import ast
import fnmatch
from dataclasses import dataclass, field
from pathlib import Path
from types import SimpleNamespace
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.druidlint.core import Finding, LintConfig, ModuleContext, rule
from tools.druidlint.rules import (_FUNC_DEFS, _decorator_names, _dotted,
                                   _is_lockish, _terminal)

# ---------------------------------------------------------------------------
# Identities
# ---------------------------------------------------------------------------
# lock id:   "path::Class._lock" or "path::NAME" (module-level)
# state id:  ("attr", "path::Class", attr) | ("global", path, name)
# func id:   "path::Qual.name" (Qual includes nesting: "f.<locals>.Handler")

LOCK_CTORS = {"Lock": "lock", "RLock": "rlock"}
HANDLER_BASES = {"BaseHTTPRequestHandler", "StreamRequestHandler",
                 "BaseRequestHandler"}
#: methods that construction-phase writes are exempt in — nothing else can
#: hold a reference to the instance yet
INIT_METHODS = {"__init__", "__new__", "__post_init__", "__set_name__"}
#: in-place mutations of a container attribute count as writes to it
MUTATORS = {"append", "appendleft", "extend", "insert", "add", "update",
            "pop", "popitem", "popleft", "remove", "discard", "clear",
            "setdefault", "move_to_end", "sort", "reverse",
            "__setitem__", "__delitem__"}
#: root kinds that imply concurrent instances of the SAME root (a pool
#: worker races its siblings; an HTTP handler races other requests)
CONCURRENT_KINDS = {"submit", "map", "handler", "extra"}

UNKNOWN_LOCK = "?unknown-lock?"


@dataclass(frozen=True)
class Site:
    path: str
    line: int
    col: int


@dataclass
class LockDef:
    lock_id: str
    kind: str                         # "lock" | "rlock" | "condition"
    site: Site                        # construction call site

    @property
    def reentrant(self) -> bool:
        return self.kind == "rlock"


@dataclass
class FuncInfo:
    func_id: str
    path: str
    name: str
    qual: str                         # dotted qualname within module
    node: ast.AST = None
    class_key: Optional[str] = None   # "path::Class" of owning class
    #: events, each with the LOCAL with-held set at that point
    acquires: List[Tuple[str, Tuple[str, ...], Site, bool]] = \
        field(default_factory=list)   # (lock, held, site, via with-stmt)
    calls: List[Tuple[str, Tuple[str, ...], Site, bool]] = \
        field(default_factory=list)   # (callee, held, site, receiver=self)
    writes: List[Tuple[Tuple, Tuple[str, ...], Site]] = \
        field(default_factory=list)   # (state, held, site)
    reads: List[Tuple[Tuple, Tuple[str, ...], Site]] = \
        field(default_factory=list)
    #: cached own-statement list (several passes re-traverse it)
    own: Optional[List[ast.AST]] = None


@dataclass
class ClassInfo:
    class_key: str                    # "path::Qual"
    path: str
    qual: str
    bases: List[ast.expr] = field(default_factory=list)
    methods: Dict[str, str] = field(default_factory=dict)   # name → func_id
    locks: Dict[str, LockDef] = field(default_factory=dict)  # attr → lock
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr → class
    #: container attrs (`self._nodes: Dict[str, DataNode]`) → element class
    elem_types: Dict[str, str] = field(default_factory=dict)
    #: container attrs → "mapping" | "sequence" (plain iteration yields
    #: elements only for sequences; mappings yield keys)
    elem_kind: Dict[str, str] = field(default_factory=dict)
    properties: Set[str] = field(default_factory=set)
    is_handler: bool = False


@dataclass
class ModuleInfo:
    path: str
    tree: ast.AST
    #: import binding: local name → ("module", path) | ("symbol", path, name)
    imports: Dict[str, Tuple] = field(default_factory=dict)
    #: module-level name → ("class", key) | ("func", id) | ("lock", id)
    #:                   | ("instance", class_key) | ("var",)
    globals: Dict[str, Tuple] = field(default_factory=dict)
    locks: Dict[str, LockDef] = field(default_factory=dict)


@dataclass
class Program:
    modules: Dict[str, ModuleInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)
    locks: Dict[str, LockDef] = field(default_factory=dict)
    sources: Dict[str, str] = field(default_factory=dict)
    roots: Dict[str, str] = field(default_factory=dict)      # func_id → kind
    #: program functions that escape as plain callable values (entry lock
    #: context unknowable, assumed empty)
    escaped: Set[str] = field(default_factory=set)
    #: dataflow results
    must_held: Dict[str, Optional[Set[str]]] = field(default_factory=dict)
    may_held: Dict[str, Set[str]] = field(default_factory=dict)
    roots_of: Dict[str, Set[str]] = field(default_factory=dict)
    #: lock-order graph: (a, b) → representative Site (a held while b taken)
    order_edges: Dict[Tuple[str, str], Site] = field(default_factory=dict)
    #: precomputed findings: rule → path → [(line, col, message)]
    findings: Dict[str, Dict[str, List[Tuple[int, int, str]]]] = \
        field(default_factory=dict)
    #: memoized per-function local binding frames (built once, used by the
    #: event walk AND root discovery)
    frames: Dict[str, Dict[str, Tuple]] = field(default_factory=dict)
    #: class → direct program subclasses (dynamic-dispatch over-approx)
    subclasses: Dict[str, Set[str]] = field(default_factory=dict)
    #: memo: method func_id → [func_id of it + every subclass override]
    _dispatch: Dict[str, List[str]] = field(default_factory=dict)
    #: queued higher-order edges: (callee, lambda-body callee, site)
    _pending_callbacks: List[Tuple[str, str, Site]] = \
        field(default_factory=list)

    def lock_sites(self) -> Dict[Tuple[str, int], str]:
        """(path, lineno) of every lock construction → lock id; the dynamic
        witness maps runtime locks back to static identity through this."""
        return {(l.site.path, l.site.line): l.lock_id
                for l in self.locks.values()}


# ---------------------------------------------------------------------------
# Module collection
# ---------------------------------------------------------------------------

def _pattern_prefix(pat: str) -> str:
    """Literal directory prefix of a glob pattern ('druid_tpu/*' →
    'druid_tpu') — walking only these keeps the scan off .git and friends."""
    lead = []
    for part in pat.split("/")[:-1]:
        if any(c in part for c in "*?["):
            break
        lead.append(part)
    return "/".join(lead)


def _raceguard_paths(root: Path, config: LintConfig) -> List[Path]:
    pats = config.raceguard_modules
    scan_roots = {(_pattern_prefix(p) or ".") for p in pats}
    seen: Set[Path] = set()
    out: List[Path] = []
    for sr in sorted(scan_roots):
        base = root / sr if sr != "." else root
        if not base.is_dir():
            continue
        for p in sorted(base.rglob("*.py")):
            rel = p.relative_to(root).as_posix()
            if "__pycache__" in rel or p in seen:
                continue
            if any(fnmatch.fnmatch(rel, pat) or rel == pat for pat in pats):
                seen.add(p)
                out.append(p)
    return sorted(out)


def program_sig(root: Path, config: LintConfig) -> str:
    """Identity of the whole analyzed program: any member file changing
    must invalidate every module's cached raceguard findings."""
    parts = []
    for p in _raceguard_paths(root, config):
        try:
            st = p.stat()
            parts.append(f"{p.relative_to(root).as_posix()}:"
                         f"{st.st_mtime_ns}:{st.st_size}")
        except OSError:
            parts.append(f"{p}:gone")
    return "|".join(parts)


_PROGRAM_CACHE: Dict[str, Tuple[str, Program]] = {}


def analyze_tree(root, config: LintConfig) -> Program:
    """Analyze the on-disk program under `root` (memoized on program_sig)."""
    root = Path(root).resolve()
    key = str(root)
    sig = program_sig(root, config) + "|" + repr(
        (sorted(config.raceguard_modules),
         sorted(config.extra_thread_roots),
         sorted(config.raceguard_assume_edges)))
    hit = _PROGRAM_CACHE.get(key)
    if hit is not None and hit[0] == sig:
        return hit[1]
    sources = {}
    for p in _raceguard_paths(root, config):
        try:
            sources[p.relative_to(root).as_posix()] = p.read_text()
        except (OSError, UnicodeDecodeError):
            continue
    prog = analyze_sources(sources, config)
    _PROGRAM_CACHE[key] = (sig, prog)
    return prog


def analyze_sources(sources: Dict[str, str], config: LintConfig) -> Program:
    prog = Program(sources=dict(sources))
    for path, src in sorted(sources.items()):
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue                  # core reports syntax errors itself
        _collect_module(prog, path, tree)
    _bind_and_walk(prog, config)
    _find_roots(prog, config)
    _dataflow(prog)
    _order_graph(prog, config)
    _compute_findings(prog, config)
    return prog


# ---- pass 1: declarations -------------------------------------------------

def _module_path_of(dotted: str) -> str:
    return dotted.replace(".", "/") + ".py"


def _module_lookup(prog: Program, dotted_path: str) -> Optional[str]:
    """Program path for a module reference — plain module or package
    __init__ ('druid_tpu/native.py' → 'druid_tpu/native/__init__.py')."""
    if dotted_path in prog.modules:
        return dotted_path
    pkg = dotted_path[:-3] + "/__init__.py"
    return pkg if pkg in prog.modules else None


def _lock_ctor_kind(call: ast.AST) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    return LOCK_CTORS.get(_terminal(call.func))


def _collect_module(prog: Program, path: str, tree: ast.AST) -> None:
    mod = ModuleInfo(path=path, tree=tree)
    prog.modules[path] = mod
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                mod.imports[name] = ("module", _module_path_of(target))
        elif isinstance(node, ast.ImportFrom):
            if node.level or not node.module:
                continue
            src = _module_path_of(node.module)
            for alias in node.names:
                mod.imports[alias.asname or alias.name] = \
                    ("symbol", src, alias.name)

    def visit(body, qual_prefix, class_key):
        for node in body:
            if isinstance(node, ast.ClassDef):
                qual = f"{qual_prefix}{node.name}"
                ck = f"{path}::{qual}"
                ci = ClassInfo(class_key=ck, path=path, qual=qual,
                               bases=list(node.bases))
                ci.is_handler = any(_terminal(b) in HANDLER_BASES
                                    for b in node.bases)
                prog.classes[ck] = ci
                if class_key is None and not qual_prefix.count("<locals>"):
                    mod.globals.setdefault(node.name, ("class", ck))
                visit(node.body, f"{qual}.", ck)
            elif isinstance(node, _FUNC_DEFS):
                qual = f"{qual_prefix}{node.name}"
                fid = f"{path}::{qual}"
                fi = FuncInfo(func_id=fid, path=path, name=node.name,
                              qual=qual, node=node, class_key=class_key)
                prog.funcs[fid] = fi
                if class_key is not None:
                    ci = prog.classes[class_key]
                    ci.methods[node.name] = fid
                    if _decorator_names(node) & {"property",
                                                 "cached_property"}:
                        ci.properties.add(node.name)
                if class_key is None and qual_prefix == "":
                    mod.globals.setdefault(node.name, ("func", fid))
                visit(node.body, f"{qual}.<locals>.", None)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                kind = _lock_ctor_kind(node.value)
                if isinstance(t, ast.Name) and class_key is None \
                        and qual_prefix == "":
                    if kind is not None:
                        lid = f"{path}::{t.id}"
                        ld = LockDef(lid, kind,
                                     Site(path, node.value.lineno,
                                          node.value.col_offset))
                        mod.locks[t.id] = ld
                        prog.locks[lid] = ld
                        mod.globals[t.id] = ("lock", lid)
                    else:
                        mod.globals.setdefault(t.id, ("var",))

    visit(tree.body, "", None)

    # instance lock attrs + condition aliases: any `self.X = Lock()` inside
    # a method (scan after classes exist so nesting order doesn't matter)
    for ck, ci in list(prog.classes.items()):
        if ci.path != path:
            continue
        for mname, fid in ci.methods.items():
            fi = prog.funcs[fid]
            self_name = _self_param(fi.node)
            if self_name is None:
                continue
            for node in _own(fi):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == self_name):
                    continue
                kind = _lock_ctor_kind(node.value)
                if kind is not None:
                    lid = f"{ck}.{t.attr}"
                    ci.locks.setdefault(
                        t.attr, LockDef(lid, kind,
                                        Site(path, node.value.lineno,
                                             node.value.col_offset)))
                    prog.locks.setdefault(lid, ci.locks[t.attr])
                elif isinstance(node.value, ast.Call) \
                        and _terminal(node.value.func) == "Condition":
                    args = node.value.args
                    if args and isinstance(args[0], ast.Attribute) \
                            and isinstance(args[0].value, ast.Name) \
                            and args[0].value.id == self_name \
                            and args[0].attr in ci.locks:
                        # Condition(self._lock) IS self._lock
                        ci.locks[t.attr] = ci.locks[args[0].attr]
                    else:
                        lid = f"{ck}.{t.attr}"
                        ci.locks.setdefault(
                            t.attr,
                            LockDef(lid, "condition",
                                    Site(path, node.value.lineno,
                                         node.value.col_offset)))
                        prog.locks.setdefault(lid, ci.locks[t.attr])


def _self_param(fn: ast.AST) -> Optional[str]:
    args = fn.args
    if "staticmethod" in _decorator_names(fn):
        return None
    if args.args:
        return args.args[0].arg
    return None


def _own_nodes(fn: ast.AST):
    """fn's own statements/expressions, excluding nested def/class BODIES
    (those are separate FuncInfos / ClassInfos with their own scopes); the
    def/class statement itself is yielded so bindings can see it."""
    stack = list(_body_of(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_DEFS + (ast.ClassDef,)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _own(fi: FuncInfo) -> List[ast.AST]:
    if fi.own is None:
        fi.own = list(_own_nodes(fi.node))
    return fi.own


# ---- pass 2: binder + per-function events ---------------------------------

class _Scope:
    """Lexical scope chain for value resolution: function-local single
    assignments, enclosing functions (closures), then module globals."""

    def __init__(self, mod: ModuleInfo, frames: List[Dict[str, Tuple]]):
        self.mod = mod
        self.frames = frames          # innermost last

    def lookup(self, name: str) -> Optional[Tuple]:
        for frame in reversed(self.frames):
            if name in frame:
                return frame[name]
        g = self.mod.globals.get(name)
        if g is not None:
            return g
        imp = self.mod.imports.get(name)
        if imp is not None:
            return ("import",) + imp
        return None


def _bind_and_walk(prog: Program, config: LintConfig) -> None:
    for path, mod in prog.modules.items():
        # module-level instance bindings: NAME = Class(...)
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                ck = _resolve_class(prog, mod, _Scope(mod, []),
                                    node.value.func)
                if ck is not None:
                    mod.globals[node.targets[0].id] = ("instance", ck)
    # class attribute types: `self.X = Class(...)` / `self.X = param` with
    # an annotated param / `self.X = param or Class(...)`
    for ck, ci in prog.classes.items():
        mod = prog.modules[ci.path]
        for fid in ci.methods.values():
            fi = prog.funcs[fid]
            self_name = _self_param(fi.node)
            if self_name is None:
                continue
            frame = _param_bindings(prog, mod, fi)
            scope = _Scope(mod, [frame])
            for node in _own(fi):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute) \
                        and isinstance(node.targets[0].value, ast.Name) \
                        and node.targets[0].value.id == self_name:
                    got = _resolve_value(prog, mod, scope, node.value)
                    if got is not None and got[0] == "instance":
                        ci.attr_types.setdefault(node.targets[0].attr,
                                                 got[1])
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Attribute) \
                        and isinstance(node.target.value, ast.Name) \
                        and node.target.value.id == self_name:
                    # `self._nodes: Dict[str, DataNode] = {}` — element
                    # type flows to .get()/.setdefault()/indexing results
                    tck = _resolve_annotation(prog, mod, scope,
                                              node.annotation)
                    if tck is not None:
                        ci.attr_types.setdefault(node.target.attr, tck)
                    else:
                        eck = _elem_annotation(prog, mod, scope,
                                               node.annotation)
                        if eck is not None:
                            ci.elem_types.setdefault(node.target.attr, eck)
                            ci.elem_kind.setdefault(
                                node.target.attr,
                                _container_kind(node.annotation))
    _build_subclass_map(prog)
    _ctor_param_attr_pass(prog)
    # per-function event walks
    for fid, fi in prog.funcs.items():
        _walk_function(prog, fi)
    # higher-order hops: the callback may run under any lock its receiver
    # acquires internally
    for callee, inner, site in prog._pending_callbacks:
        tfi = prog.funcs.get(callee)
        if tfi is None:
            continue
        held = tuple(sorted({l for l, _h, _s, _w in tfi.acquires
                             if l != UNKNOWN_LOCK}))
        tfi.calls.append((inner, held, site, False))


def _ctor_param_attr_pass(prog: Program) -> None:
    """Type constructor-stored params from their CALL SITES: `self.clock =
    clock or (...)` in LeaderParticipant.__init__ plus a program call
    `LeaderParticipant(..., clock=self.clock)` where the argument resolves
    to a ManualClock types `LeaderParticipant.clock` — closing the
    callable-attribute gap annotations alone cannot (the param is just
    `clock: Optional[Callable]`)."""
    # per class: __init__ param name → attrs assigned from it
    param_attrs: Dict[str, Dict[str, List[str]]] = {}
    for ck, ci in prog.classes.items():
        init = ci.methods.get("__init__")
        if init is None:
            continue
        fi = prog.funcs[init]
        self_name = _self_param(fi.node)
        if self_name is None:
            continue
        pmap: Dict[str, List[str]] = {}
        for node in _own(fi):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and isinstance(node.targets[0].value, ast.Name) \
                    and node.targets[0].value.id == self_name:
                v = node.value
                if isinstance(v, ast.BoolOp):
                    v = v.values[0]       # `param or default`
                if isinstance(v, ast.Name):
                    pmap.setdefault(v.id, []).append(node.targets[0].attr)
        if pmap:
            param_attrs[ck] = pmap
    for fid in sorted(prog.funcs):
        fi = prog.funcs[fid]
        mod = prog.modules[fi.path]
        scope = _Scope(mod, [_param_bindings(prog, mod, fi)])
        for node in _own(fi):
            if not isinstance(node, ast.Call):
                continue
            got = _resolve_value(prog, mod, scope, node.func)
            if got is None or got[0] != "class":
                continue
            ci = prog.classes.get(got[1])
            pmap = param_attrs.get(got[1])
            if ci is None or not pmap or "__init__" not in ci.methods:
                continue
            init_fi = prog.funcs[ci.methods["__init__"]]
            params = [a.arg for a in init_fi.node.args.args][1:]
            bound: Dict[str, ast.AST] = {}
            for i, a in enumerate(node.args):
                if i < len(params):
                    bound[params[i]] = a
            for kw in node.keywords:
                if kw.arg:
                    bound[kw.arg] = kw.value
            for pname, expr in bound.items():
                attrs = pmap.get(pname)
                if not attrs:
                    continue
                v = _resolve_value(prog, mod, scope, expr)
                if v is not None and v[0] == "instance":
                    for attr in attrs:
                        ci.attr_types.setdefault(attr, v[1])


def _resolve_import(prog: Program, binding: Tuple,
                    _depth: int = 0) -> Optional[Tuple]:
    """('import', 'module'|'symbol', ...) → program binding or None."""
    if binding[1] == "module":
        path = _module_lookup(prog, binding[2])
        return ("module", path) if path is not None else None
    _, _, src, name = binding
    src_path = _module_lookup(prog, src)
    target = prog.modules.get(src_path) if src_path is not None else None
    if target is not None:
        got = target.globals.get(name)
        if got is not None:
            return got
        imp = target.imports.get(name)      # re-export chain, bounded
        if imp is not None and imp[0] == "symbol" and _depth < 8:
            got = _resolve_import(prog, ("import",) + imp, _depth + 1)
            if got is not None:
                return got
    # `from pkg import mod` / `from pkg.mod import name` where the name is
    # itself a submodule (the package __init__ need not mention it)
    sub = _module_lookup(prog, src[:-3] + "/" + name + ".py")
    return ("module", sub) if sub is not None else None


def _resolve_value(prog: Program, mod: ModuleInfo, scope: _Scope,
                   expr: ast.AST) -> Optional[Tuple]:
    """Resolve an expression to ('instance', class_key) | ('class', ck) |
    ('func', fid) | ('module', path) | ('lock', lid) | ('var',) | None."""
    if isinstance(expr, ast.Name):
        b = scope.lookup(expr.id)
        if b is None:
            return None
        if b[0] == "import":
            return _resolve_import(prog, b)
        return b
    if isinstance(expr, ast.Attribute):
        base = _resolve_value(prog, mod, scope, expr.value)
        if base is None:
            return None
        if base[0] == "module":
            target = prog.modules.get(base[1])
            if target is None:
                return None
            got = target.globals.get(expr.attr)
            if got is not None:
                return got
            imp = target.imports.get(expr.attr)
            if imp is not None:
                return _resolve_import(prog, ("import",) + imp)
            return None
        if base[0] == "instance":
            ci = _class_with(prog, base[1], expr.attr)
            if ci is None:
                return None
            if expr.attr in ci.locks:
                return ("lock", ci.locks[expr.attr].lock_id)
            if expr.attr in ci.attr_types:
                return ("instance", ci.attr_types[expr.attr])
            if expr.attr in ci.elem_types:
                return ("container", ci.elem_types[expr.attr],
                        ci.elem_kind.get(expr.attr, "mapping"))
            if expr.attr in ci.properties:
                # a property ACCESS is a call, not a callable value: the
                # expression's type is the property's return annotation
                pnode = prog.funcs[ci.methods[expr.attr]].node
                if getattr(pnode, "returns", None) is not None:
                    mod2 = prog.modules[ci.path]
                    ck = _resolve_annotation(prog, mod2, _Scope(mod2, []),
                                             pnode.returns)
                    if ck is not None:
                        return ("instance", ck)
                return None
            if expr.attr in ci.methods:
                return ("func", ci.methods[expr.attr])
            return None
        if base[0] == "class":
            ci = _class_with(prog, base[1], expr.attr)
            if ci is not None and expr.attr in ci.methods:
                return ("func", ci.methods[expr.attr])
            return None
        return None
    if isinstance(expr, ast.Subscript):
        base = _resolve_value(prog, mod, scope, expr.value)
        if base is not None and base[0] == "container":
            return ("instance", base[1])
        return None
    if isinstance(expr, ast.Call):
        # container getters hand back the element: self._nodes.get(name)
        if isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in _CONTAINER_GETTERS:
            base = _resolve_value(prog, mod, scope, expr.func.value)
            if base is not None and base[0] == "container":
                return ("instance", base[1])
        fn = _resolve_value(prog, mod, scope, expr.func)
        if fn is not None and fn[0] == "class":
            return ("instance", fn[1])
        if fn is not None and fn[0] == "func":
            fi = prog.funcs.get(fn[1])
            if fi is not None and getattr(fi.node, "returns", None) is not None:
                # the annotation's names live in the FUNCTION'S module
                fmod = prog.modules[fi.path]
                ck = _resolve_annotation(prog, fmod, _Scope(fmod, []),
                                         fi.node.returns)
                if ck is not None:
                    return ("instance", ck)
        return None
    if isinstance(expr, ast.BoolOp):
        # `cache_config or CacheConfig()`: any resolvable operand types it
        for op in reversed(expr.values):
            got = _resolve_value(prog, mod, scope, op)
            if got is not None:
                return got
        return None
    if isinstance(expr, ast.IfExp):
        return _resolve_value(prog, mod, scope, expr.body) \
            or _resolve_value(prog, mod, scope, expr.orelse)
    return None


_MAPPING_HEADS = {"Dict", "dict", "OrderedDict", "DefaultDict",
                  "defaultdict", "Mapping", "MutableMapping"}
_SEQUENCE_HEADS = {"List", "list", "Set", "set", "Sequence", "Iterable",
                   "Tuple", "tuple", "Deque", "deque", "FrozenSet",
                   "frozenset"}
_CONTAINER_HEADS = _MAPPING_HEADS | _SEQUENCE_HEADS
_CONTAINER_GETTERS = {"get", "setdefault", "pop", "popleft", "popitem"}


def _container_kind(ann: ast.AST) -> str:
    """"mapping" or "sequence" for a container annotation head (after
    unwrapping Optional and quoted forms the way _elem_annotation does)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return "mapping"
    if isinstance(ann, ast.Subscript):
        head = _terminal(ann.value)
        if head == "Optional":
            return _container_kind(ann.slice)
        if head in _SEQUENCE_HEADS:
            return "sequence"
    return "mapping"


def _elem_annotation(prog: Program, mod: ModuleInfo, scope: _Scope,
                     ann: ast.AST) -> Optional[str]:
    """Element class of a container annotation: Dict[K, V] → V,
    List[V] → V (the type of what indexing/get/setdefault hands back)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        head = _terminal(ann.value)
        if head == "Optional":
            return _elem_annotation(prog, mod, scope, ann.slice)
        if head not in _CONTAINER_HEADS:
            return None
        inner = ann.slice
        if isinstance(inner, ast.Tuple) and inner.elts:
            inner = inner.elts[-1]        # Dict[K, V] → V
        return _resolve_annotation(prog, mod, scope, inner)
    return None


def _resolve_annotation(prog: Program, mod: ModuleInfo, scope: _Scope,
                        ann: ast.AST) -> Optional[str]:
    """A type annotation resolved to a program class key (handles
    Optional[X]/List[X] one level and "quoted" forward references)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        got = scope.lookup(ann.value)
        if got is not None and got[0] == "import":
            got = _resolve_import(prog, got)
        return got[1] if got is not None and got[0] == "class" else None
    if isinstance(ann, ast.Subscript):
        if _terminal(ann.value) in ("Optional", "List", "Sequence", "Dict",
                                    "Tuple", "Set", "Iterable"):
            inner = ann.slice
            if _terminal(ann.value) != "Optional":
                return None       # container ELEMENT types are not the value
            return _resolve_annotation(prog, mod, scope, inner)
        return None
    got = _resolve_value(prog, mod, scope, ann)
    return got[1] if got is not None and got[0] == "class" else None


def _resolve_class(prog: Program, mod: ModuleInfo, scope: _Scope,
                   expr: ast.AST) -> Optional[str]:
    got = _resolve_value(prog, mod, scope, expr)
    return got[1] if got is not None and got[0] == "class" else None


def _build_subclass_map(prog: Program) -> None:
    for ck, ci in prog.classes.items():
        mod = prog.modules[ci.path]
        scope = _Scope(mod, [])
        for b in ci.bases:
            bck = _resolve_class(prog, mod, scope, b)
            if bck is not None:
                prog.subclasses.setdefault(bck, set()).add(ck)


def _dispatch_targets(prog: Program, fid: str) -> List[str]:
    """A call resolved to a method may dynamically dispatch to any program
    subclass override — `store: LeaseStore` receiving a MetadataLeaseStore
    must contribute the override's acquisitions to the MAY order graph.
    Returns [fid] plus every transitive-subclass override (memoized)."""
    got = prog._dispatch.get(fid)
    if got is not None:
        return got
    out = [fid]
    fi = prog.funcs.get(fid)
    if fi is not None and fi.class_key is not None:
        seen: Set[str] = set()
        stack = list(prog.subclasses.get(fi.class_key, ()))
        while stack:
            ck = stack.pop()
            if ck in seen:
                continue
            seen.add(ck)
            sub = prog.classes.get(ck)
            if sub is not None:
                override = sub.methods.get(fi.name)
                if override is not None and override != fid:
                    out.append(override)
            stack.extend(prog.subclasses.get(ck, ()))
    prog._dispatch[fid] = out
    return out


def _base_method_fid(prog: Program, class_key: str, name: str,
                     _depth: int = 0) -> Optional[str]:
    """func_id of the nearest BASE-class definition of `name`."""
    ci = prog.classes.get(class_key)
    if ci is None or _depth > 4:
        return None
    mod = prog.modules[ci.path]
    for b in ci.bases:
        bck = _resolve_class(prog, mod, _Scope(mod, []), b)
        if bck is None:
            continue
        bci = prog.classes.get(bck)
        if bci is not None and name in bci.methods:
            return bci.methods[name]
        got = _base_method_fid(prog, bck, name, _depth + 1)
        if got is not None:
            return got
    return None


def _class_with(prog: Program, class_key: str, attr: str,
                _depth: int = 0) -> Optional[ClassInfo]:
    """The class (or base class, resolved through the program) that defines
    `attr` as a lock / typed attribute / method."""
    ci = prog.classes.get(class_key)
    if ci is None or _depth > 4:
        return None
    if attr in ci.locks or attr in ci.attr_types or attr in ci.methods \
            or attr in ci.elem_types:
        return ci
    mod = prog.modules[ci.path]
    for b in ci.bases:
        bck = _resolve_class(prog, mod, _Scope(mod, []), b)
        if bck is not None:
            got = _class_with(prog, bck, attr, _depth + 1)
            if got is not None:
                return got
    return None


def _param_bindings(prog: Program, mod: ModuleInfo,
                    fi: FuncInfo) -> Dict[str, Tuple]:
    """self + annotated parameters: `def __init__(self, node: DataNode)`
    binds `node` to a DataNode instance. An override that drops the base's
    annotations inherits them by parameter name (the Monitor.do_monitor
    pattern: the base declares `emitter: ServiceEmitter`, overrides
    don't)."""
    frame: Dict[str, Tuple] = {}
    self_name = _self_param(fi.node) if fi.class_key else None
    if self_name is not None:
        frame[self_name] = ("instance", fi.class_key)

    def bind_from(fn, ann_mod: ModuleInfo):
        scope = _Scope(ann_mod, [])
        args = fn.args
        for a in list(args.args) + list(args.kwonlyargs) + \
                list(getattr(args, "posonlyargs", ())):
            if a.arg in frame or a.annotation is None:
                continue
            ck = _resolve_annotation(prog, ann_mod, scope, a.annotation)
            if ck is not None:
                frame[a.arg] = ("instance", ck)

    bind_from(fi.node, mod)
    if fi.class_key is not None:
        base_fid = _base_method_fid(prog, fi.class_key, fi.name)
        if base_fid is not None and base_fid != fi.func_id:
            base_fi = prog.funcs[base_fid]
            # base annotations resolve in the BASE's module (its imports)
            bind_from(base_fi.node, prog.modules[base_fi.path])
    return frame


def _stmt_store_names(node: ast.AST) -> List[str]:
    """Names a STATEMENT binds in function scope (assignment/loop/with
    targets, walrus) — comprehension targets are excluded by construction
    (ast.comprehension is not matched; its target is only reachable
    through the comprehension node itself)."""
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.For,
                           ast.NamedExpr)):
        targets = [node.target]
    elif isinstance(node, ast.withitem):
        targets = [node.optional_vars] if node.optional_vars else []
    else:
        return []
    return [n.id for t in targets for n in ast.walk(t)
            if isinstance(n, ast.Name)]


def _local_frame(prog: Program, mod: ModuleInfo, fi: FuncInfo,
                 outer_frames: List[Dict[str, Tuple]]) -> Dict[str, Tuple]:
    """Single-assignment local bindings inside one function: `x = self`,
    `x = Class(...)`, `x = self.view.node(...)` (return annotation),
    `x = imported_name`, annotated parameters — and ITERATION ELEMENTS:
    `for rs in self._replicas.values()` binds rs to the Dict's value
    class, `for n in self._nodes` to a List's element class, `for k, rs
    in self._replicas.items()` binds rs — so the order graph and guard
    rules extend into replica-set/timeline-style loop bodies."""
    frame: Dict[str, Tuple] = _param_bindings(prog, mod, fi)
    params = set(frame)
    assigned_twice: Set[str] = set()

    def bind(name: str, got: Optional[Tuple]) -> None:
        if name in assigned_twice:
            return
        norm = None
        if got is not None:
            norm = got[:2] if got[0] == "instance" else got
        if name in frame and name not in params:
            if norm is not None and frame[name] == norm:
                # REBINDING to the same identity (`worker = Worker()` ...
                # `worker = Worker()`): the name's class is still known, so
                # closures that captured it keep resolving — dropping it
                # here silently lost their order edges. Only a CONFLICTING
                # or unresolvable rebinding degrades to unknown.
                return
            del frame[name]
            assigned_twice.add(name)
            return
        if norm is not None:
            frame[name] = norm
        elif name in params:
            del frame[name]           # reassigned param: binding unknown
            assigned_twice.add(name)

    def iter_element(it: ast.AST, scope: _Scope) -> Optional[Tuple]:
        """Element binding of an iteration source: .values()/.items()
        hand back mapping values; plain iteration yields elements only
        for sequence-kind containers."""
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("values", "items"):
            base = _resolve_value(prog, mod, scope, it.func.value)
            if base is not None and base[0] == "container":
                return ("instance", base[1])
            return None
        got = _resolve_value(prog, mod, scope, it)
        if got is not None and got[0] == "container" \
                and len(got) > 2 and got[2] == "sequence":
            return ("instance", got[1])
        return None

    def iter_bindings(node) -> List[Tuple[str, Optional[Tuple]]]:
        """(name, binding) pairs an iteration construct (For statement or
        comprehension generator) establishes for its target."""
        scope = _Scope(mod, outer_frames + [dict(frame)])
        elem = iter_element(node.iter, scope)
        tgt = node.target
        if isinstance(tgt, ast.Name):
            # plain target over .items() iterates pairs, not values
            is_items = isinstance(node.iter, ast.Call) \
                and isinstance(node.iter.func, ast.Attribute) \
                and node.iter.func.attr == "items"
            return [(tgt.id, None if is_items else elem)]
        if isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2 \
                and isinstance(tgt.elts[1], ast.Name) \
                and isinstance(node.iter, ast.Call) \
                and isinstance(node.iter.func, ast.Attribute) \
                and node.iter.func.attr == "items":
            return [(tgt.elts[1].id, elem)]
        return []

    comp_nodes: List[ast.comprehension] = []
    stmt_bound: Set[str] = set(params)

    for node in _own(fi):
        for name in _stmt_store_names(node):
            stmt_bound.add(name)
        if isinstance(node, _FUNC_DEFS):
            nested = f"{fi.path}::{fi.qual}.<locals>.{node.name}"
            if nested in prog.funcs:
                frame.setdefault(node.name, ("func", nested))
        elif isinstance(node, ast.ClassDef):
            nested = f"{fi.path}::{fi.qual}.<locals>.{node.name}"
            if nested in prog.classes:
                frame.setdefault(node.name, ("class", nested))
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            scope = _Scope(mod, outer_frames + [dict(frame)])
            bind(node.targets[0].id,
                 _resolve_value(prog, mod, scope, node.value))
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            # `nodes: List[DataNode] = []` — a typed local container
            scope = _Scope(mod, outer_frames + [dict(frame)])
            tck = _resolve_annotation(prog, mod, scope, node.annotation)
            if tck is not None:
                bind(node.target.id, ("instance", tck))
            else:
                eck = _elem_annotation(prog, mod, scope, node.annotation)
                if eck is not None:
                    bind(node.target.id,
                         ("container", eck, _container_kind(node.annotation)))
        elif isinstance(node, ast.For):
            for name, got in iter_bindings(node):
                bind(name, got)
        elif isinstance(node, ast.comprehension):
            comp_nodes.append(node)
    # Comprehension targets are their OWN scope in py3 — they never leak
    # into function locals, so they must neither invalidate nor fabricate
    # a statement-level binding (bind() treats a second write as
    # "reassigned: unknown", which would silently drop the typed local and
    # its order edges). Bind them only for names no statement stores, so
    # calls inside the comprehension body still resolve.
    for node in comp_nodes:
        for name, got in iter_bindings(node):
            if name not in stmt_bound and name not in assigned_twice:
                bind(name, got)
    return frame


def _walk_function(prog: Program, fi: FuncInfo) -> None:
    mod = prog.modules[fi.path]
    scope = _Scope(mod, _closure_frames(prog, mod, fi)
                   + [_frame_of(prog, mod, fi)])
    self_name = _self_param(fi.node) if fi.class_key else None
    tracked_globals = _tracked_globals(mod)

    def resolve_lock(expr) -> Optional[str]:
        got = _resolve_value(prog, mod, scope, expr)
        if got is not None and got[0] == "lock":
            return got[1]
        return None

    def state_of(expr) -> Optional[Tuple]:
        """A shared-state identity for an attribute/global expression."""
        if isinstance(expr, ast.Attribute):
            base = _resolve_value(prog, mod, scope, expr.value)
            if base is not None and base[0] == "instance":
                return ("attr", base[1], expr.attr)
            return None
        if isinstance(expr, ast.Name) and expr.id in tracked_globals:
            return ("global", fi.path, expr.id)
        return None

    def site(node) -> Site:
        return Site(fi.path, getattr(node, "lineno", 1),
                    getattr(node, "col_offset", 0))

    def _manual_lock_stmt(node, which: str) -> Optional[str]:
        """`X.acquire()` / `X.release()` as a bare statement on a
        RESOLVED project lock — the manual held-region protocol. Unknown
        lockish receivers stay event-only (extending held with UNKNOWN
        would grant benefit-of-the-doubt skips the code didn't earn)."""
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == which:
                return resolve_lock(call.func.value)
        return None

    def walk(body, held: Tuple[str, ...]):
        held = tuple(held)
        for node in body:
            if isinstance(node, _FUNC_DEFS + (ast.ClassDef, ast.Lambda)):
                continue              # nested defs walk as their own funcs
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    lid = resolve_lock(item.context_expr)
                    if lid is None and _is_lockish(item.context_expr):
                        lid = UNKNOWN_LOCK
                    if lid is not None:
                        if lid not in inner:
                            fi.acquires.append(
                                (lid, inner, site(item.context_expr), True))
                            inner = inner + (lid,)
                    else:
                        _expr_events(item.context_expr, held)
                walk(node.body, inner)
                continue
            # manual held regions: a statement-level `X.acquire()` holds X
            # for the REST of this block (or until a statement-level
            # release); `try: … finally: X.release()` releases after the
            # Try. Both lock-set dataflows see the region through the held
            # tuples recorded on every event inside it.
            mlid = _manual_lock_stmt(node, "acquire")
            if mlid is not None:
                fi.acquires.append((mlid, held, site(node.value), False))
                if mlid not in held:
                    held = held + (mlid,)
                continue
            rlid = _manual_lock_stmt(node, "release")
            if rlid is not None:
                if rlid in held:
                    held = tuple(l for l in held if l != rlid)
                continue
            if isinstance(node, ast.Try):
                released = set()
                for st in node.finalbody:
                    r = _manual_lock_stmt(st, "release")
                    if r is not None:
                        released.add(r)
                walk(node.body, held)
                for h in node.handlers:
                    walk(h.body, held)
                walk(node.orelse, held)
                walk(node.finalbody, held)
                if released:
                    held = tuple(l for l in held if l not in released)
                continue
            _stmt_events(node, held)
            for sub in _child_blocks(node):
                walk(sub, held)

    def _child_blocks(node):
        out = []
        for name in ("body", "orelse", "finalbody"):
            b = getattr(node, name, None)
            if b:
                out.append(b)
        for h in getattr(node, "handlers", []) or []:
            out.append(h.body)
        return out

    def _stmt_events(node, held):
        # statement-level stores first (so reads in values still record)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                _store_events(t, held)
            _expr_events(node.value, held)
        elif isinstance(node, ast.AugAssign):
            _store_events(node.target, held)
            st = state_of(node.target)
            if st is not None:
                fi.reads.append((st, held, site(node.target)))
            _expr_events(node.value, held)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                _store_events(node.target, held)
                _expr_events(node.value, held)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                _store_events(t, held)
        elif isinstance(node, (ast.Expr, ast.Return, ast.Raise, ast.Assert,
                               ast.If, ast.While, ast.For)):
            for v in (getattr(node, "value", None),
                      getattr(node, "test", None),
                      getattr(node, "iter", None),
                      getattr(node, "exc", None)):
                if v is not None:
                    _expr_events(v, held)
        else:
            for v in ast.iter_child_nodes(node):
                if isinstance(v, ast.expr):
                    _expr_events(v, held)

    def _store_events(target, held):
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                _store_events(e, held)
            return
        if isinstance(target, ast.Subscript):
            st = state_of(target.value)
            if st is not None:
                fi.writes.append((st, held, site(target)))
            _expr_events(target.slice, held)
            return
        st = state_of(target)
        if st is not None:
            fi.writes.append((st, held, site(target)))
        elif isinstance(target, ast.Name) and fi.class_key is None \
                and target.id in tracked_globals \
                and _has_global_decl(fi.node, target.id):
            fi.writes.append((("global", fi.path, target.id), held,
                              site(target)))

    def _expr_events(expr, held):
        for node in ast.walk(expr):
            if isinstance(node, _FUNC_DEFS + (ast.Lambda,)):
                continue
            if isinstance(node, ast.Call):
                _call_events(node, held)
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                parent_is_call = False  # handled via _call_events receivers
                st = state_of(node)
                if st is not None and not parent_is_call:
                    fi.reads.append((st, held, site(node)))
                # @property access counts as a call to the property method
                base = _resolve_value(prog, mod, scope, node.value)
                if base is not None and base[0] == "instance":
                    ci = _class_with(prog, base[1], node.attr)
                    if ci is not None and node.attr in ci.properties:
                        for target in _dispatch_targets(
                                prog, ci.methods[node.attr]):
                            fi.calls.append((target, held, site(node),
                                             _is_self_expr(node.value)))
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in tracked_globals:
                fi.reads.append((("global", fi.path, node.id), held,
                                 site(node)))

    def _is_self_expr(expr) -> bool:
        return self_name is not None and isinstance(expr, ast.Name) \
            and expr.id == self_name

    def _call_events(call: ast.Call, held):
        func = call.func
        # .acquire() on a resolvable lock: an acquisition event (edges
        # target it) without extending the held set (release is untracked)
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            lid = resolve_lock(func.value)
            if lid is None and _is_lockish(func.value):
                lid = UNKNOWN_LOCK
            if lid is not None:
                fi.acquires.append((lid, held, site(call), False))
                return
        # mutator method on a tracked state: a write
        if isinstance(func, ast.Attribute) and func.attr in MUTATORS:
            st = state_of(func.value)
            if st is not None:
                fi.writes.append((st, held, site(call)))
        got = _resolve_value(prog, mod, scope, func)
        targets: List[str] = []
        if got is not None:
            if got[0] == "func":
                recv_self = isinstance(func, ast.Attribute) \
                    and _is_self_expr(func.value)
                for target in _dispatch_targets(prog, got[1]):
                    fi.calls.append((target, held, site(call),
                                     recv_self and target == got[1]))
                    targets.append(target)
            elif got[0] == "class":
                ci = prog.classes.get(got[1])
                if ci is not None and "__init__" in ci.methods:
                    fi.calls.append((ci.methods["__init__"], held,
                                     site(call), False))
                    targets.append(ci.methods["__init__"])
            elif got[0] == "instance":
                # calling an instance invokes __call__ (ManualClock-style
                # callable objects stored as attributes)
                ci = _class_with(prog, got[1], "__call__")
                if ci is not None:
                    for target in _dispatch_targets(
                            prog, ci.methods["__call__"]):
                        fi.calls.append((target, held, site(call), False))
                        targets.append(target)
        # a lambda argument is a callback the callee may invoke while
        # holding ITS locks (TaskLockbox.critical_section runs fn() under
        # self._lock): queue synthetic callee→lambda-body call edges; the
        # post-walk pass attaches the callee's own acquired-lock set as the
        # held context (not knowable until every function is walked)
        if targets:
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                if not isinstance(arg, ast.Lambda):
                    continue
                for sub in ast.walk(arg.body):
                    if not isinstance(sub, ast.Call):
                        continue
                    inner = _resolve_value(prog, mod, scope, sub.func)
                    if inner is not None and inner[0] == "func":
                        for t in targets:
                            prog._pending_callbacks.append(
                                (t, inner[1], site(sub)))
        # thread-root constructions + escaped callables handled in
        # _find_roots (they need the full program first)

    walk(_body_of(fi.node), ())


def _body_of(fn):
    return fn.body if not isinstance(fn, ast.Lambda) else [ast.Expr(fn.body)]


def _has_global_decl(fn, name: str) -> bool:
    for node in _own_nodes(fn):
        if isinstance(node, ast.Global) and name in node.names:
            return True
    return False


def _tracked_globals(mod: ModuleInfo) -> Set[str]:
    """Module-level mutable bindings worth tracking: plain vars (not
    classes/funcs/locks/imports)."""
    return {n for n, b in mod.globals.items() if b[0] in ("var", "instance")}


def _frame_of(prog: Program, mod: ModuleInfo,
              fi: FuncInfo) -> Dict[str, Tuple]:
    got = prog.frames.get(fi.func_id)
    if got is None:
        got = _local_frame(prog, mod, fi, _closure_frames(prog, mod, fi))
        prog.frames[fi.func_id] = got
    return got


def _closure_frames(prog: Program, mod: ModuleInfo,
                    fi: FuncInfo) -> List[Dict[str, Tuple]]:
    """Binding frames of lexically enclosing functions (outermost first) —
    resolves the `outer = self` nested-HTTP-handler idiom."""
    frames: List[Dict[str, Tuple]] = []
    parts = fi.qual.split(".<locals>.")
    prefix = ""
    for part in parts[:-1]:
        prefix = f"{prefix}.<locals>.{part}" if prefix else part
        # the enclosing def may itself be a method: its qual is `prefix`
        outer = prog.funcs.get(f"{fi.path}::{prefix}")
        if outer is not None:
            frames.append(_frame_of(prog, mod, outer))
    return frames


# ---- pass 3: thread roots -------------------------------------------------

def _find_roots(prog: Program, config: LintConfig) -> None:
    escaped: Set[str] = set()

    for fid, fi in prog.funcs.items():
        mod = prog.modules[fi.path]
        scope = _Scope(mod, _closure_frames(prog, mod, fi)
                       + [_frame_of(prog, mod, fi)])

        def resolve_func(expr) -> Optional[str]:
            got = _resolve_value(prog, mod, scope, expr)
            if got is not None and got[0] == "func":
                return got[1]
            if got is not None and got[0] == "class":
                ci = prog.classes.get(got[1])
                if ci is not None and "__call__" in ci.methods:
                    return ci.methods["__call__"]
            return None

        for node in _own(fi):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal(node.func)
            cand: List[Tuple[ast.AST, str]] = []
            if name in ("Thread", "Timer"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        cand.append((kw.value, "thread"))
                if name == "Timer" and len(node.args) >= 2:
                    cand.append((node.args[1], "thread"))
            elif name == "submit" and isinstance(node.func, ast.Attribute):
                if node.args:
                    cand.append((node.args[0], "submit"))
            elif name == "map" and isinstance(node.func, ast.Attribute):
                if node.args:
                    cand.append((node.args[0], "map"))
            elif name == "finalize" and node.args and len(node.args) >= 2:
                cand.append((node.args[1], "finalizer"))
            for expr, kind in cand:
                target = resolve_func(expr)
                if target is not None:
                    for t in _dispatch_targets(prog, target):
                        prog.roots.setdefault(t, kind)
            # any program function passed as a plain argument escapes:
            # its entry lock context is unknowable, assume none
            args = list(node.args) + [kw.value for kw in node.keywords]
            for a in args:
                if isinstance(a, (ast.Name, ast.Attribute)):
                    t = resolve_func(a)
                    if t is not None:
                        escaped.add(t)

    # HTTP handler methods: every request runs them on a fresh server thread
    for ci in prog.classes.values():
        if not ci.is_handler:
            continue
        for mname, fid in ci.methods.items():
            if mname.startswith("do_"):
                prog.roots.setdefault(fid, "handler")

    # configured roots: "path-glob::qual-glob"
    for pat in config.extra_thread_roots:
        ppat, _, qpat = pat.partition("::")
        for fid, fi in prog.funcs.items():
            if fnmatch.fnmatch(fi.path, ppat) and \
                    fnmatch.fnmatch(fi.qual, qpat or "*"):
                prog.roots.setdefault(fid, "extra")

    prog.escaped = escaped            # consumed by _dataflow


# ---- pass 4: dataflow -----------------------------------------------------

def _dataflow(prog: Program) -> None:
    callers: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
    for fid, fi in prog.funcs.items():
        for callee, held, _site, _self in fi.calls:
            callers.setdefault(callee, []).append((fid, held))

    entry_zero = set(prog.roots) | prog.escaped | \
        {fid for fid in prog.funcs if fid not in callers}

    # MUST (intersection): TOP = None
    must: Dict[str, Optional[Set[str]]] = {fid: None for fid in prog.funcs}
    for fid in entry_zero:
        must[fid] = set()
    changed = True
    while changed:
        changed = False
        for fid, fi in prog.funcs.items():
            if must[fid] is None:
                continue
            base = must[fid]
            for callee, held, _s, _self in fi.calls:
                if callee not in must:
                    continue
                cand = base | set(held) - {UNKNOWN_LOCK}
                cur = must[callee]
                new = cand if cur is None else cur & cand
                if new != cur:
                    must[callee] = new
                    changed = True
    prog.must_held = must

    # MAY (union)
    may: Dict[str, Set[str]] = {fid: set() for fid in prog.funcs}
    changed = True
    while changed:
        changed = False
        for fid, fi in prog.funcs.items():
            for callee, held, _s, _self in fi.calls:
                if callee not in may:
                    continue
                cand = may[fid] | set(held) - {UNKNOWN_LOCK}
                if not cand <= may[callee]:
                    may[callee] |= cand
                    changed = True
    prog.may_held = may

    # root reachability
    roots_of: Dict[str, Set[str]] = {fid: set() for fid in prog.funcs}
    for fid in prog.roots:
        roots_of[fid].add(fid)
    changed = True
    while changed:
        changed = False
        for fid, fi in prog.funcs.items():
            if not roots_of[fid]:
                continue
            for callee, _h, _s, _self in fi.calls:
                if callee in roots_of and not roots_of[fid] <= roots_of[callee]:
                    roots_of[callee] |= roots_of[fid]
                    changed = True
    prog.roots_of = roots_of


def _eff_held(prog: Program, fid: str, held: Tuple[str, ...]) -> Set[str]:
    """MUST-effective held set at an event site."""
    entry = prog.must_held.get(fid)
    base = set() if entry is None else set(entry)
    return (base | set(held)) - {UNKNOWN_LOCK}


def _has_unknown(held: Tuple[str, ...]) -> bool:
    return UNKNOWN_LOCK in held


# ---- pass 5: lock-order graph ---------------------------------------------

def _order_graph(prog: Program, config: Optional[LintConfig] = None) -> None:
    edges: Dict[Tuple[str, str], Site] = {}
    if config is not None:
        for decl in config.raceguard_assume_edges:
            a, _, b = decl.partition("->")
            a, b = a.strip(), b.strip()
            if a and b and a != b:
                edges[(a, b)] = Site("<assumed>", 0, 0)
    for fid, fi in prog.funcs.items():
        may = prog.may_held.get(fid, set())
        for lock, held, site, _via_with in fi.acquires:
            if lock == UNKNOWN_LOCK:
                continue
            for h in may | (set(held) - {UNKNOWN_LOCK}):
                if h == lock:
                    continue          # self-edges handled separately
                key = (h, lock)
                old = edges.get(key)
                if old is None or (site.path, site.line) < (old.path,
                                                            old.line):
                    edges[key] = site
    prog.order_edges = edges


def _self_deadlocks(prog: Program) -> List[Tuple[str, Site, str]]:
    """`with self.L:` reaching another acquisition of self.L through a
    SELF-call chain (same instance, provably) on a non-reentrant Lock."""
    out = []
    for ck, ci in prog.classes.items():
        for attr, ld in ci.locks.items():
            if ld.reentrant or ld.kind == "condition":
                continue
            # methods of this class that acquire the lock
            acquirers: Dict[str, Site] = {}
            for mname, fid in ci.methods.items():
                for lock, _h, site, _w in prog.funcs[fid].acquires:
                    if lock == ld.lock_id:
                        acquirers.setdefault(fid, site)
            if not acquirers:
                continue
            # self-call closure from each holder's with-body
            self_calls: Dict[str, Set[str]] = {}
            for mname, fid in ci.methods.items():
                outs = set()
                for callee, _h, _s, recv_self in prog.funcs[fid].calls:
                    if recv_self and callee in prog.funcs:
                        outs.add(callee)
                self_calls[fid] = outs
            for fid in ci.methods.values():
                fi = prog.funcs[fid]
                for callee, held, csite, recv_self in fi.calls:
                    if not recv_self or ld.lock_id not in held:
                        continue
                    seen: Set[str] = set()
                    stack = [callee]
                    while stack:
                        cur = stack.pop()
                        if cur in seen:
                            continue
                        seen.add(cur)
                        if cur in acquirers:
                            out.append((ld.lock_id, acquirers[cur],
                                        f"reached from {fi.qual}() which "
                                        f"already holds it"))
                            stack = []
                            break
                        stack.extend(self_calls.get(cur, ()))
    return out


# ---- pass 6: findings -----------------------------------------------------

def _lock_short(lock_id: str) -> str:
    return lock_id.split("::", 1)[-1]


def _state_short(state: Tuple) -> str:
    if state[0] == "attr":
        return f"{state[1].split('::', 1)[-1]}.{state[2]}"
    return f"{state[1]}:{state[2]}"


def _compute_findings(prog: Program, config: LintConfig) -> None:
    add = _adder(prog)

    # collect events per state
    state_writes: Dict[Tuple, List[Tuple[str, Tuple, Site]]] = {}
    state_reads: Dict[Tuple, List[Tuple[str, Tuple, Site]]] = {}
    for fid, fi in prog.funcs.items():
        init = fi.name in INIT_METHODS
        for st, held, site in fi.writes:
            if not init:
                state_writes.setdefault(st, []).append((fid, held, site))
        for st, held, site in fi.reads:
            if not init:
                state_reads.setdefault(st, []).append((fid, held, site))

    # unguarded-shared-write
    for st, writes in sorted(state_writes.items()):
        locked, unlocked = [], []
        for fid, held, site in writes:
            if _has_unknown(held):
                continue              # benefit of the doubt
            (locked if _eff_held(prog, fid, held) else unlocked).append(
                (fid, held, site))
        if locked and unlocked:
            guards = sorted({_lock_short(l) for f, h, s in locked
                             for l in _eff_held(prog, f, h)})
            for fid, held, site in sorted(unlocked,
                                          key=lambda w: (w[2].path,
                                                         w[2].line)):
                add("unguarded-shared-write", site,
                    f"{_state_short(st)} is written under "
                    f"{'/'.join(guards)} elsewhere but written here with "
                    f"no lock held — one interleaving away from lost "
                    f"updates; guard it or make it thread-local")
            continue
        # variant b: concurrent roots, no common lock across all writes.
        # Only states with a SHARING signal participate: module globals,
        # or attributes of a class that declares a lock — a lockless class
        # reached from a handler is usually per-request (its instances
        # never cross threads), and flagging every plan/builder object
        # would drown the real races
        owner = prog.classes.get(st[1]) if st[0] == "attr" else None
        if st[0] != "global" and (owner is None or not owner.locks):
            continue
        weight = 0
        root_names = set()
        for fid, held, site in writes:
            for r in prog.roots_of.get(fid, ()):
                kind = prog.roots.get(r, "thread")
                weight = max(weight,
                             2 if kind in CONCURRENT_KINDS else 1)
                root_names.add(prog.funcs[r].qual if r in prog.funcs else r)
        if len(root_names) >= 2:
            weight = 2
        common = None
        for fid, held, site in writes:
            eff = _eff_held(prog, fid, held)
            common = eff if common is None else (common & eff)
        if weight >= 2 and writes and not common \
                and not any(_has_unknown(h) for _f, h, _s in writes):
            fid, held, site = min(writes, key=lambda w: (w[2].path,
                                                         w[2].line))
            add("unguarded-shared-write", site,
                f"{_state_short(st)} is written from concurrent thread "
                f"roots ({', '.join(sorted(root_names)[:3])}) with no "
                f"common lock — concurrent writers race; pick one lock "
                f"for every write")

    # guard-consistency
    for st, writes in sorted(state_writes.items()):
        guard = None
        ok = True
        for fid, held, site in writes:
            if _has_unknown(held):
                ok = False
                break
            eff = _eff_held(prog, fid, held)
            if not eff:
                ok = False            # unguarded-shared-write territory
                break
            guard = eff if guard is None else (guard & eff)
        if not ok or not guard:
            continue
        writer_rooted = any(prog.roots_of.get(fid) for fid, _h, _s in writes)
        if not writer_rooted:
            continue                  # no concurrent writer can exist
        gnames = "/".join(sorted(_lock_short(g) for g in guard))
        for fid, held, site in sorted(state_reads.get(st, ()),
                                      key=lambda r: (r[2].path, r[2].line)):
            if not prog.roots_of.get(fid):
                continue              # not on a thread-root path
            if _has_unknown(held):
                continue
            if _eff_held(prog, fid, held) & guard:
                continue
            add("guard-consistency", site,
                f"{_state_short(st)} is consistently written under "
                f"{gnames} but read here without it on a thread-root "
                f"path — a concurrent writer can interleave; take the "
                f"lock or snapshot under it")

    # lock-order-cycle
    sccs = _tarjan(_edge_graph(prog.order_edges))
    for scc in sccs:
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        sites = sorted((s for (a, b), s in prog.order_edges.items()
                        if a in scc and b in scc),
                       key=lambda s: (s.path, s.line))
        # anchor at a REAL acquisition site — an assumed (config-declared)
        # edge has no line to suppress on
        real = [s for s in sites if s.path != "<assumed>"]
        if not real:
            continue
        names = " -> ".join(_lock_short(l) for l in cyc) + \
            f" -> {_lock_short(cyc[0])}"
        add("lock-order-cycle", real[0],
            f"lock acquisition order cycle: {names} — two threads "
            f"entering from opposite ends deadlock; impose one global "
            f"order (or merge the locks)")
    for lock_id, site, how in _self_deadlocks(prog):
        add("lock-order-cycle", site,
            f"{_lock_short(lock_id)} is non-reentrant but re-acquired "
            f"here, {how} — same-thread re-entry deadlocks; use RLock "
            f"or split a _locked helper")

    # lock-in-traced is computed per-module in the rule body (needs no
    # cross-module state); nothing precomputed here


def _edge_graph(edges) -> Dict[str, Set[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    return graph


def _adder(prog: Program):
    def add(rule_name: str, site: Site, message: str) -> None:
        prog.findings.setdefault(rule_name, {}).setdefault(
            site.path, []).append((site.line, site.col, message))
    return add


def _tarjan(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strong(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                out.append(scc)

    for v in sorted(graph):
        if v not in index:
            strong(v)
    return out


# ---------------------------------------------------------------------------
# DOT rendering (CLI --dot)
# ---------------------------------------------------------------------------

def render_dot(prog: Program) -> str:
    """The static lock-order graph as graphviz DOT; cycle members red."""
    in_cycle: Set[str] = set()
    for scc in _tarjan(_edge_graph(prog.order_edges)):
        if len(scc) > 1:
            in_cycle |= scc
    lines = ["digraph lock_order {", '  rankdir=LR;',
             '  node [shape=box, fontsize=10];']
    nodes = sorted({n for e in prog.order_edges for n in e})
    for n in nodes:
        color = ', color=red' if n in in_cycle else ''
        lines.append(f'  "{n}" [label="{_lock_short(n)}"{color}];')
    for (a, b), site in sorted(prog.order_edges.items()):
        if site.path == "<assumed>":
            lines.append(f'  "{a}" -> "{b}" [style=dashed, '
                         f'label="assumed (config)", fontsize=8];')
        else:
            lines.append(f'  "{a}" -> "{b}" '
                         f'[label="{site.path}:{site.line}", fontsize=8];')
    lines.append("}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Rule registration (per-module shims over the program index)
# ---------------------------------------------------------------------------

def _program_for(ctx: ModuleContext) -> Program:
    """The whole-program index this module's findings come from. One lint
    run = one LintConfig instance, so the disk program is memoized ON the
    config (analyze_tree's sig check — a stat of every member file — would
    otherwise rerun for every (rule × module) pair)."""
    root = Path(ctx.config.root).resolve()
    prog = getattr(ctx.config, "_raceguard_program", None)
    if prog is None or getattr(ctx.config, "_raceguard_root", None) != root:
        prog = analyze_tree(root, ctx.config)
        ctx.config._raceguard_program = prog
        ctx.config._raceguard_root = root
    if prog.sources.get(ctx.path) == ctx.source:
        return prog
    # unit-test path (check_source with synthetic source): the module is
    # its own one-file program
    return analyze_sources({ctx.path: ctx.source}, ctx.config)


def _emit(ctx: ModuleContext, prog: Program,
          rule_name: str) -> Iterable[Finding]:
    for line, col, message in sorted(
            prog.findings.get(rule_name, {}).get(ctx.path, ())):
        yield ctx.finding(SimpleNamespace(lineno=line, col_offset=col),
                          message)


def _in_scope(ctx: ModuleContext) -> bool:
    return ctx.path_matches(ctx.config.raceguard_modules)


@rule("unguarded-shared-write", "error",
      "shared attribute written with inconsistent (or no) locking")
def check_unguarded_shared_write(ctx: ModuleContext) -> Iterable[Finding]:
    """An attribute (or module global) written under a lock in one place
    and with no lock in another — or written from two concurrent thread
    roots with no common lock — races: lost updates on counters, torn
    composite state, dict resize vs iteration. Whole-program: the writes
    and the threads that reach them may live in different modules (config
    `raceguard-modules`). Constructor writes (`__init__`) are exempt."""
    if not _in_scope(ctx):
        return
    yield from _emit(ctx, _program_for(ctx), "unguarded-shared-write")


@rule("lock-order-cycle", "error",
      "cycle in the static lock-acquisition-order graph")
def check_lock_order_cycle(ctx: ModuleContext) -> Iterable[Finding]:
    """Lock A held while taking lock B in one path and B held while taking
    A in another deadlocks the moment both paths run concurrently — the
    bug ships silently on low-traffic CPU tests and bites under TPU-scale
    fan-out. Also flags same-lock re-entry through a self-call chain on a
    non-reentrant Lock. The dynamic witness (lockwitness.py) checks every
    RUNTIME acquisition order is an edge of this static graph."""
    if not _in_scope(ctx):
        return
    yield from _emit(ctx, _program_for(ctx), "lock-order-cycle")


@rule("guard-consistency", "warning",
      "guarded attribute read without its lock on a thread-root path")
def check_guard_consistency(ctx: ModuleContext) -> Iterable[Finding]:
    """If every (post-construction) write of an attribute happens under one
    lock, reads on thread-root-reachable paths must hold it too: unlocked
    readers see torn multi-field invariants and racing iterator/resize
    states. Reads in code no spawned thread reaches are left alone, as are
    attributes whose writers are all construction-time."""
    if not _in_scope(ctx):
        return
    yield from _emit(ctx, _program_for(ctx), "guard-consistency")


@rule("lock-in-traced", "error",
      "lock acquired inside traced/compiled device code")
def check_lock_in_traced(ctx: ModuleContext) -> Iterable[Finding]:
    """A `with lock:` (or .acquire()) inside a jit/shard_map/pallas-traced
    body runs ONCE at trace time — it guards nothing on later executions,
    and holding a Python lock across a compiled dispatch invites deadlock
    with the host threads that feed it. Take locks at the dispatch layer,
    never inside traced functions."""
    if not _in_scope(ctx):
        return
    from tools.druidlint.rules import _collect_traced_functions
    extra = frozenset({"pallas_call"})
    # nested defs inside a traced body are NOT pruned on purpose: a helper
    # defined (and called) during tracing runs at trace time too, so its
    # lock acquisitions are just as inert
    for fn in _collect_traced_functions(ctx, extra):
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    if _is_lockish(item.context_expr):
                        yield ctx.finding(
                            item.context_expr,
                            f"with {_dotted(item.context_expr)}: inside "
                            f"traced {getattr(fn, 'name', '<fn>')}() — "
                            f"runs once at trace time, guards nothing at "
                            f"execution; lock at the dispatch layer")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire" \
                    and _is_lockish(node.func.value):
                yield ctx.finding(
                    node, f"{_dotted(node.func)}() inside traced "
                          f"{getattr(fn, 'name', '<fn>')}() — runs once "
                          f"at trace time, guards nothing at execution")
