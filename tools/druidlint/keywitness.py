"""keywitness: a dynamic witness for keyguard's cache-key soundness rules.

keyguard proves statically that every build input has dataflow into its
cache key — but a dataflow edge is not an EQUALITY: `sig` can fold
`spec.dims` and still collapse two distinct descriptor sets into one
string. The witness closes that loop by observing reality: it wraps the
build-on-miss caches the engine actually runs (grouping/batching jit
caches, the sharded-fn cache, the device segment pool) and records, for
every build, a canonical structural FINGERPRINT of the build inputs next
to the cache key it was stored under. Two builds under the SAME key with
DIFFERENT fingerprints is a key collision — exactly the silent aliasing
the static rule exists to prevent, caught in vivo.

Mechanics:
  * install() swaps each cache's module-global OrderedDict for a
    recording subclass (hit/insert counters; `release_device_caches`
    uses .clear(), so wrappers survive engine cache drops) and wraps the
    module-global builder functions (`_build_device_fn`,
    `_build_batched_fn`, `_build_sharded_fn`). A builder call computes
    the fingerprint of its arguments and parks it thread-locally; the
    insert that immediately follows (same thread, under the cache lock)
    claims it for its key. DeviceSegmentPool.get_or_build is wrapped
    directly: every access fingerprints the returned entry's pytree
    structure under the (owner,)+key identity — a key whose resident
    value changes structure between accesses aliased two stagings.
  * Fingerprints are STRUCTURAL, never data: arrays contribute
    (dtype, ndim) for builder arguments (per-segment id arrays arrive
    as runtime arguments, their lengths legitimately vary under one
    key) and (dtype, shape) for pool values (a staged block's shapes
    are fixed per key). No device sync, no host reads. Fields that are
    non-structural by the engine's own contract are excluded
    (_FP_EXCLUDE): druid output-column `name`s (applied host-side;
    the traced program is positional) and scalars that ride aux as
    device arrays (uniform bucket offset/period, dim cardinality,
    const-sum value) — one program serving different values of those
    is the design.
  * Only the process-wide pool SINGLETON (devicepool._POOL at install
    time) is witnessed: tests construct isolated pools with synthetic
    owner tokens and deliberately rebuild toy keys at different sizes
    to exercise eviction accounting — out-of-contract by design.
  * The fingerprint table OUTLIVES cache eviction on purpose: a key
    rebuilt after LRU eviction must reproduce the fingerprint its first
    build recorded — key→structure is a time-invariant contract, not a
    cache-lifetime one.

Session mode mirrors lockwitness/leakwitness: DRUID_TPU_KEY_WITNESS=1
installs a process-wide singleton from tests/conftest.py and fails the
run on any collision in pytest_unconfigure. The raceguard stress test
drives a dedicated key-churn leg through the same witness.

Test-only: nothing in druid_tpu imports this module.
"""
from __future__ import annotations

import collections
import enum
import threading
from typing import Callable, Dict, List, Optional, Tuple

#: process-wide session witness (see session_witness)
_SESSION: Optional["KeyWitness"] = None

#: wrapped caches: (module name, cache global, builder global, label)
_JIT_SITES = (
    ("druid_tpu.engine.grouping", "_JIT_CACHE", "_build_device_fn",
     "grouping._JIT_CACHE"),
    ("druid_tpu.engine.batching", "_JIT_CACHE", "_build_batched_fn",
     "batching._JIT_CACHE"),
    ("druid_tpu.parallel.distributed", "_FN_CACHE", "_build_sharded_fn",
     "distributed._FN_CACHE"),
)

_POOL_LABEL = "devicepool.get_or_build"


def session_witness(root: Optional[str] = None) -> Optional["KeyWitness"]:
    """Process-wide singleton install (same double-conftest rationale as
    lockwitness.session_witness). First call (with `root`) installs;
    later calls return the same witness."""
    global _SESSION
    if _SESSION is None and root is not None:
        _SESSION = KeyWitness(root).install()
    return _SESSION


def end_session_witness() -> Optional["KeyWitness"]:
    """Uninstall and detach the session witness (reporting hook)."""
    global _SESSION
    w, _SESSION = _SESSION, None
    if w is not None:
        w.uninstall()
    return w


# ---------------------------------------------------------------------------
# Structural fingerprints
# ---------------------------------------------------------------------------

#: fields excluded from structural fingerprints, per class name ("*"
#: applies everywhere). Two kinds of field live here, both NON-structural
#: by the engine's own contract:
#:   * presentation — `name` is the druid output-column label, applied
#:     host-side when SegmentPartial.states is assembled; the traced
#:     program is positional, so one program serving two output names is
#:     the design, not a collision (`field`/`column` attrs, which SELECT
#:     inputs, stay in).
#:   * aux-riding values — scalars the builder ships as device arrays
#:     (grouping._assemble_aux): uniform bucket offset/period, dim
#:     cardinality, the constant-sum value. Their VALUES are runtime
#:     data under one compiled program. Scalars that ARE trace constants
#:     (K, n_intervals, chunk_rows, mm_base, num_total) stay in.
_FP_EXCLUDE: Dict[str, frozenset] = {
    "*": frozenset({"name"}),
    "GroupSpec": frozenset({"uniform_first_offset", "uniform_period"}),
    "KeyDim": frozenset({"cardinality"}),
    "SumKernel": frozenset({"const_value"}),
    # `round` is applied in HllKernel.finalize_array, host-side np.rint
    # on the already-materialized registers — the device program is
    # identical either way
    "CardinalityAggregator": frozenset({"round"}),
    "HyperUniqueAggregator": frozenset({"round"}),
}


def _fp(obj, shapes: bool, depth: int = 8) -> str:
    """Canonical structural fingerprint. Deterministic within a process,
    data-free: arrays contribute dtype + ndim (or full shape when
    `shapes`), objects contribute class name + sorted field structure
    minus the _FP_EXCLUDE presentation/aux fields. Lists and tuples
    canonicalize to one spelling — builder args are consumed by python
    closure construction, never as pytree leaves, so the container
    flavor cannot shape the built program."""
    if depth <= 0:
        return f"<{type(obj).__name__}>"
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return repr(obj)
    if isinstance(obj, enum.Enum):
        return f"{type(obj).__name__}.{obj.name}"
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):
        dim = tuple(obj.shape) if shapes else getattr(obj, "ndim", "?")
        return f"arr({obj.dtype},{dim})"
    if isinstance(obj, (tuple, list, set, frozenset)):
        is_set = isinstance(obj, (set, frozenset))
        items = sorted(obj, key=repr) if is_set else obj
        body = ",".join(_fp(x, shapes, depth - 1) for x in items)
        return f"{'set' if is_set else 'seq'}[{body}]"
    if isinstance(obj, dict):
        body = ",".join(
            f"{k!r}:{_fp(v, shapes, depth - 1)}"
            for k, v in sorted(obj.items(), key=lambda kv: repr(kv[0])))
        return f"dict[{body}]"
    fields = getattr(obj, "_fields", None)          # namedtuples
    if fields is None and hasattr(obj, "__dict__"):
        fields = sorted(vars(obj))
    if fields:
        skip = _FP_EXCLUDE["*"] | _FP_EXCLUDE.get(type(obj).__name__,
                                                  frozenset())
        body = ",".join(
            f"{f}={_fp(getattr(obj, f, None), shapes, depth - 1)}"
            for f in fields if f not in skip)
        return f"{type(obj).__name__}({body})"
    r = repr(obj)
    return r if " at 0x" not in r else f"<{type(obj).__name__}>"


def fingerprint_args(*args, shapes: bool = False) -> str:
    return ";".join(_fp(a, shapes) for a in args)


# ---------------------------------------------------------------------------
# Recording cache
# ---------------------------------------------------------------------------

class RecordingCache(collections.OrderedDict):
    """Drop-in OrderedDict that reports gets/inserts to the witness. An
    insert claims the thread's parked builder fingerprint (the build and
    the insert run back-to-back on one thread, under the cache's lock)."""

    def __init__(self, witness: "KeyWitness", label: str, items=()):
        self._witness = witness
        self._label = label
        super().__init__()
        self._prime(items)

    def _prime(self, items) -> None:
        """Adopt warm entries WITHOUT the recording __setitem__: carried
        entries are not builds, and claiming a parked fingerprint here
        would mis-attribute some in-flight build's structure to an
        unrelated warm key (the nested-witness hand-back does exactly
        this iteration)."""
        for k, v in items:
            collections.OrderedDict.__setitem__(self, k, v)

    def get(self, key, default=None):
        got = super().get(key, default)
        self._witness._count("hit" if got is not default else "miss",
                            self._label)
        return got

    def __setitem__(self, key, value):
        fp = self._witness._take_pending(self._label)
        if fp is not None:
            self._witness.record(self._label, key, fp)
        super().__setitem__(key, value)


class KeyWitness:
    """Holds observed state for one install()/uninstall() span."""

    def __init__(self, root: str):
        self.root = root
        self._meta = threading.Lock()
        self._tls = threading.local()
        #: (cache label, key) → first observed build fingerprint
        self.fingerprints: Dict[Tuple[str, object], str] = {}
        #: same-key/different-fingerprint observations
        self.collisions: List[str] = []
        #: per-label event counters: builds / hits / misses
        self.counts: Dict[str, Dict[str, int]] = {}
        self._installed = False
        self._saved: List[Tuple[object, str, object]] = []
        #: the production pool singleton captured at install(); accesses
        #: through any OTHER pool instance (test fixtures) are unrecorded
        self._prod_pool: Optional[object] = None

    # ---- recording ------------------------------------------------------
    def _count(self, kind: str, label: str) -> None:
        with self._meta:
            self.counts.setdefault(label, {})[kind] = \
                self.counts.setdefault(label, {}).get(kind, 0) + 1

    def _park_pending(self, label: str, fp: str) -> None:
        pend = getattr(self._tls, "pending", None)
        if pend is None:
            pend = self._tls.pending = {}
        pend[label] = fp

    def _take_pending(self, label: str) -> Optional[str]:
        pend = getattr(self._tls, "pending", None)
        return None if pend is None else pend.pop(label, None)

    def record(self, label: str, key, fp: str) -> None:
        """One observed build of `key` in cache `label` from inputs with
        structural fingerprint `fp`."""
        self._count("build", label)
        with self._meta:
            old = self.fingerprints.get((label, key))
            if old is None:
                self.fingerprints[(label, key)] = fp
            elif old != fp:
                # show a window AROUND the first divergence — the common
                # prefix is usually hundreds of identical dataclass fields
                i = next((j for j, (a, b) in enumerate(zip(old, fp))
                          if a != b), min(len(old), len(fp)))
                lo = max(0, i - 60)
                self.collisions.append(
                    f"{label} key {key!r}: two builds with different "
                    f"input structure — diverge at char {i}: "
                    f"first ...{old[lo:i + 160]!r}, "
                    f"now ...{fp[lo:i + 160]!r}")

    # ---- install/uninstall ---------------------------------------------
    def install(self) -> "KeyWitness":
        if self._installed:
            return self
        import importlib
        witness = self
        for mod_name, cache_attr, builder_attr, label in _JIT_SITES:
            mod = importlib.import_module(mod_name)
            real_builder: Callable = getattr(mod, builder_attr)

            def make_wrapper(real=real_builder, lbl=label):
                def wrapped(*args, **kwargs):
                    witness._park_pending(
                        lbl, fingerprint_args(*args, shapes=False)
                        + (f";{sorted(kwargs)}" if kwargs else ""))
                    return real(*args, **kwargs)
                return wrapped

            self._saved.append((mod, builder_attr, real_builder))
            setattr(mod, builder_attr, make_wrapper())
            cache = getattr(mod, cache_attr)
            self._saved.append((mod, cache_attr, cache))
            setattr(mod, cache_attr,
                    RecordingCache(witness, label, cache.items()))

        from druid_tpu.data import devicepool
        real_gob = devicepool.DeviceSegmentPool.get_or_build
        # bind the singleton NOW: fixtures monkeypatch devicepool._POOL to
        # fresh pools, so a call-time re-read would witness those too
        self._prod_pool = devicepool._POOL

        def get_or_build(pool_self, owner, key, build):
            value = real_gob(pool_self, owner, key, build)
            if pool_self is witness._prod_pool:
                witness.record(_POOL_LABEL, (owner,) + tuple(key),
                               _fp(value, shapes=True))
            return value

        self._saved.append(
            (devicepool.DeviceSegmentPool, "get_or_build", real_gob))
        devicepool.DeviceSegmentPool.get_or_build = get_or_build
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for obj, attr, original in reversed(self._saved):
            current = getattr(obj, attr, None)
            if isinstance(current, RecordingCache) and current is not original:
                if isinstance(original, RecordingCache):
                    # nested witness (per-test inside the session-wide
                    # one): hand warm entries back to the OUTER witness's
                    # recording cache, keeping its observation intact.
                    # _prime, not update — update records each warm key
                    # as an insert and would claim the outer witness's
                    # parked fingerprint (left dangling because inner-span
                    # builds ran through BOTH builder wrappers but only
                    # the inner cache saw the insert)
                    warm = collections.OrderedDict(current)
                    original.clear()
                    original._prime(warm.items())
                else:
                    # hand the warm entries back to a plain dict — witness
                    # removal must not cold-start the engine caches
                    original = collections.OrderedDict(current)
            setattr(obj, attr, original)
        self._saved.clear()
        self._installed = False

    def __enter__(self) -> "KeyWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---- reporting ------------------------------------------------------
    def summary(self) -> str:
        with self._meta:
            builds = sum(c.get("build", 0) for c in self.counts.values())
            hits = sum(c.get("hit", 0) for c in self.counts.values())
            return (f"{len(self.fingerprints)} distinct cache key(s) "
                    f"witnessed, {builds} build(s), {hits} hit(s), "
                    f"{len(self.collisions)} collision(s)")
