"""Validate the sorted-segment windowed local-dense reduction + remaining
primitives: timeseries G=1 rate, one-hot col scaling, staging rates."""
import time
import sys
import numpy as np


def _sync(r):
    import jax
    for leaf in jax.tree.leaves(r):
        np.asarray(jax.device_get(leaf)).ravel()[:1]


def t(fn, *args, iters=3, warmup=1):
    for _ in range(warmup):
        _sync(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    N = 12_500_000
    rng = np.random.default_rng(0)
    a_np = rng.integers(0, 100, N, dtype=np.int32)
    b_np = rng.integers(0, 1000, N, dtype=np.int32)
    v_np = rng.integers(0, 10_000, N, dtype=np.int32)
    f_np = rng.normal(100, 25, N).astype(np.float32)

    # sorted layout: rows sorted by (a, b) — ingestion order
    order = np.lexsort((b_np, a_np))
    key_sorted = jnp.asarray((a_np * 1000 + b_np)[order])
    v_sorted = jnp.asarray(v_np[order])
    f_sorted = jnp.asarray(f_np[order])
    vals = jnp.asarray(v_np)
    b_ids = jnp.asarray(b_np)
    fvals = jnp.asarray(f_np)

    G = 100 * 1000
    results = {}

    # 1. timeseries-style: masked sum+count+max, G=1
    @jax.jit
    def ts(v, f):
        m = (v >= 100) & (v <= 9900)
        return (m.sum(dtype=jnp.int32),
                jnp.where(m, v, 0).sum(dtype=jnp.int64),
                jnp.where(m, f, -jnp.inf).max())
    results["timeseries_G1_3agg"] = t(ts, vals, fvals)

    # 2. windowed local-dense on sorted keys, W=128, 3 aggs + recursion L2
    BLK = 2048
    W = 128

    def windowed_pass(key, cols, nblk, blk, w):
        """key [nblk*blk] sorted-ish; returns (bases [nblk], grids)."""
        kb = key.reshape(nblk, blk)
        base = kb[:, 0][:, None]                    # block window base
        local = kb - base                           # [nblk, blk]
        ok = (local >= 0) & (local < w)             # overflow rows -> L3
        iota = jnp.arange(w, dtype=jnp.int32)
        oh = (local[:, :, None] == iota[None, None, :]) & ok[:, :, None]
        outs = []
        for c, kind in cols:
            cb = c.reshape(nblk, blk)
            if kind == "sum":
                outs.append(jnp.where(oh, cb[:, :, None], 0).sum(
                    1, dtype=jnp.int64 if cb.dtype == jnp.int32 else None))
            elif kind == "count":
                outs.append(oh.sum(1, dtype=jnp.int32))
            else:
                outs.append(jnp.where(oh, cb[:, :, None],
                                      -jnp.inf).max(1))
        return base[:, 0], outs, ok

    @jax.jit
    def windowed(key, v, f):
        nblk = N // BLK
        n = nblk * BLK
        key, v, f = key[:n], v[:n], f[:n]
        base, (cnt, sm, mx), ok = windowed_pass(
            key, [(v, "count"), (v, "sum"), (f, "max")], nblk, BLK, W)
        # L2: flatten [nblk, W] grids keyed by base+iota, scatter (small)
        keys2 = (base[:, None] + jnp.arange(W, dtype=jnp.int32)).ravel()
        keys2 = jnp.clip(keys2, 0, G - 1)
        c2 = jax.ops.segment_sum(cnt.ravel(), keys2, num_segments=G)
        s2 = jax.ops.segment_sum(sm.ravel(), keys2, num_segments=G)
        m2 = jax.ops.segment_max(mx.ravel(), keys2, num_segments=G)
        return c2, s2, m2
    results[f"windowed_sorted_W{W}_3agg+L2scatter"] = t(
        windowed, key_sorted, v_sorted, f_sorted)

    # 2b. windowed L1 only (no L2 combine) to see the split
    @jax.jit
    def windowed_l1(key, v, f):
        nblk = N // BLK
        n = nblk * BLK
        key, v, f = key[:n], v[:n], f[:n]
        base, outs, ok = windowed_pass(
            key, [(v, "count"), (v, "sum"), (f, "max")], nblk, BLK, W)
        return base, outs
    results[f"windowed_sorted_W{W}_L1only"] = t(
        windowed_l1, key_sorted, v_sorted, f_sorted)

    # 3. one-hot int8 G=1024 with 7 value cols (col scaling)
    BLK2 = 8192

    @jax.jit
    def onehot7(bk, v):
        nblk = N // BLK2
        kb = (bk[: nblk * BLK2] % 1024).reshape(nblk, BLK2)
        l = [(v[: nblk * BLK2] >> (7 * i) & 127).astype(jnp.int8).reshape(
            nblk, BLK2) for i in range(2)]
        iota = jnp.arange(1024, dtype=jnp.int32)

        def body(acc, xs):
            kk = xs[0]
            oh = (kk[:, None] == iota[None, :]).astype(jnp.int8)
            lhs = jnp.stack([jnp.ones((BLK2,), jnp.int8)] + [
                xs[1 + (i % 2)] for i in range(6)], 0)  # [7, BLK2]
            out = jax.lax.dot_general(
                lhs, oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return acc + out, None

        acc, _ = jax.lax.scan(body, jnp.zeros((7, 1024), jnp.int32),
                              (kb, *l))
        return acc
    results["onehot_int8_G1024_7col"] = t(onehot7, b_ids, vals)

    # 4. one-hot int8 single-level G=4096, 3 cols
    @jax.jit
    def onehot4096(k, v):
        nblk = N // BLK2
        kb = (k[: nblk * BLK2] % 4096).reshape(nblk, BLK2)
        v0 = (v[: nblk * BLK2] & 127).astype(jnp.int8).reshape(nblk, BLK2)
        v1 = ((v[: nblk * BLK2] >> 7) & 127).astype(jnp.int8).reshape(
            nblk, BLK2)
        iota = jnp.arange(4096, dtype=jnp.int32)

        def body(acc, xs):
            kk, l0, l1 = xs
            oh = (kk[:, None] == iota[None, :]).astype(jnp.int8)
            lhs = jnp.stack([jnp.ones((BLK2,), jnp.int8), l0, l1], 0)
            out = jax.lax.dot_general(
                lhs, oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return acc + out, None

        acc, _ = jax.lax.scan(body, jnp.zeros((3, 4096), jnp.int32),
                              (kb, v0, v1))
        return acc
    results["onehot_int8_G4096_3col"] = t(
        onehot4096, jnp.asarray(a_np * 1000 + b_np), vals)

    # 5. scatter with [N, 4] payload vs single
    key_dev = jnp.asarray(a_np * 1000 + b_np)

    @jax.jit
    def seg4(k, v):
        vv = jnp.stack([v, v + 1, v + 2, v + 3], 1)
        return jax.ops.segment_sum(vv, k, num_segments=131072)
    results["segment_sum_4col_payload"] = t(seg4, key_dev, vals)

    # 6. cumsum over N
    @jax.jit
    def cs(v):
        return jnp.cumsum(v, dtype=jnp.int64)
    results["cumsum_12.5M"] = t(cs, vals)

    # 7. H2D staging rate: 50MB column
    col = np.random.randint(0, 1000, 12_500_000).astype(np.int32)

    def h2d():
        return jax.device_put(col)
    results["H2D_50MB_col"] = t(h2d)

    # 8. D2H partial grids [128, 3072] int32
    grid = jnp.ones((128, 3072), jnp.int32)

    def d2h(g):
        return np.asarray(jax.device_get(g))
    results["D2H_1.5MB_grid"] = t(d2h, grid)

    for k, sec in results.items():
        print(f"{k:42s} {sec*1e3:9.2f} ms   {N/sec/1e6:9.0f} M rows/s")


if __name__ == "__main__":
    main()
