"""One-command on-chip validation ladder. Run on the real TPU:

    python tools/chip_suite.py [--rows N] [--skip-bench]

Stages (each gates the next):
  1. sanity     — devices visible, tiny matmul executes
  2. pallas     — the fused groupBy kernel compiles and matches the
                  mixed-strategy result exactly (chip_pallas_test inline)
  3. strategies — per-strategy timings on the headline shape so
                  select_strategy cutovers are measured, not assumed
  4. extended   — the other tracked BASELINE.md configs (timeseries,
                  selector-filtered topN, HLL cardinality, theta sketch)
  5. bench      — the full headline bench (same config the driver runs)

Exit code 0 only when every requested stage passes. This supersedes the
one-off microbench scripts; `profile_headline.py` remains for per-phase
profiling.
"""
import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(msg):
    print(msg, flush=True)


def stage_sanity() -> bool:
    import jax
    import jax.numpy as jnp
    t0 = time.time()
    devs = jax.devices()
    log(f"[sanity] devices={devs} ({time.time() - t0:.1f}s)")
    t0 = time.time()
    y = jnp.ones((512, 512)) @ jnp.ones((512, 512))
    ok = float(np.asarray(y)[0, 0]) == 512.0
    log(f"[sanity] matmul {'ok' if ok else 'WRONG'} "
        f"({time.time() - t0:.1f}s)")
    return ok


def _headline(rows: int, n_segments: int = 1):
    """The EXACT shape bench.py gates on (shared helpers in bench.py)."""
    import bench
    return bench.headline_segments(rows, n_segments), bench.headline_groupby()


class _spied_selection:
    """Record which strategy select_strategy actually returns — a forced
    strategy that falls through must not have its timing mislabeled."""

    def __enter__(self):
        from druid_tpu.engine import grouping
        self.grouping = grouping
        self.real = grouping.select_strategy
        self.chosen = []

        def spy(*a, **kw):
            out = self.real(*a, **kw)
            self.chosen.append(out[0])
            return out

        grouping.select_strategy = spy
        return self

    def __exit__(self, *exc):
        self.grouping.select_strategy = self.real


def stage_pallas(rows: int) -> bool:
    """Fused pallas kernel vs mixed strategy: exact result parity."""
    from druid_tpu.engine import QueryExecutor
    from druid_tpu.engine import pallas_agg
    if not pallas_agg.backend_ok():
        log("[pallas] backend not available (non-TPU or gated off) — skip")
        return True
    segs, q = _headline(rows)
    saved = os.environ.get("DRUID_TPU_PALLAS")

    def run_with(strategy_env):
        os.environ.pop("DRUID_TPU_PALLAS", None)
        if strategy_env is not None:
            os.environ["DRUID_TPU_PALLAS"] = strategy_env
        ex = QueryExecutor(segs)
        t0 = time.time()
        out = ex.run(q)
        warm = time.time() - t0
        t0 = time.time()
        out = ex.run(q)
        log(f"[pallas] {strategy_env or 'default'}: {len(out)} groups "
            f"(warm {warm:.1f}s, hot {time.time() - t0:.3f}s)")
        return {(r['event']['dimA'], r['event']['dimB']):
                (r['event']['rows'], r['event']['lsum'],
                 round(r['event']['fmax'], 3)) for r in out}

    try:
        got = run_with(None)            # pallas eligible
        want = run_with("0")            # XLA strategies only
    finally:
        # restore the operator's setting for the later stages
        if saved is None:
            os.environ.pop("DRUID_TPU_PALLAS", None)
        else:
            os.environ["DRUID_TPU_PALLAS"] = saved
    if got != want:
        diff = sum(1 for k in want if got.get(k) != want[k])
        log(f"[pallas] MISMATCH: {diff} differing groups of {len(want)}")
        return False
    log(f"[pallas] exact match over {len(want)} groups")
    return True


def stage_strategies(rows: int) -> bool:
    """Time each eligible groupBy strategy on the headline shape; a forced
    strategy that falls through is reported under what actually ran."""
    from druid_tpu.engine import QueryExecutor
    from druid_tpu.engine import grouping
    segs, q = _headline(rows)
    timings = {}
    forced = grouping.FORCE_STRATEGY
    for strat in ("mixed", "windowed", "projection"):
        try:
            grouping.FORCE_STRATEGY = strat
            with _spied_selection() as sel:
                ex = QueryExecutor(segs)
                ex.run(q)                      # warm
                ts = []
                for _ in range(3):
                    t0 = time.time()
                    ex.run(q)
                    ts.append(time.time() - t0)
            actual = sel.chosen[-1] if sel.chosen else strat
            label = strat if actual == strat \
                else f"{strat}->fell-through-to-{actual}"
            timings[label] = min(ts)
            log(f"[strategies] {label}: {min(ts) * 1e3:.0f}ms "
                f"({rows / min(ts) / 1e6:.0f}M rows/s)")
        except Exception as e:
            log(f"[strategies] {strat}: failed — {type(e).__name__}: "
                f"{str(e)[:120]}")
        finally:
            grouping.FORCE_STRATEGY = forced
    if timings:
        best = min(timings, key=timings.get)
        log(f"[strategies] best: {best} ({timings[best] * 1e3:.0f}ms)")
    return bool(timings)


def stage_extended(rows: int) -> bool:
    """The OTHER tracked BASELINE.md configs: Wikipedia-style timeseries
    (count+longSum), selector-filtered TopN with doubleSum, HLL
    cardinality, theta sketch — rates per config on the headline data."""
    from druid_tpu.engine import QueryExecutor
    from druid_tpu.query.aggregators import (CountAggregator,
                                             DoubleSumAggregator,
                                             HyperUniqueAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.filters import SelectorFilter
    from druid_tpu.query.model import TimeseriesQuery, TopNQuery
    import bench
    segs = bench.headline_segments(rows, 1)
    iv = bench.headline_interval()
    sel = list(segs[0].dims["dimA"].dictionary.values)[0]
    import druid_tpu.ext  # noqa: F401 (theta aggregator)
    from druid_tpu.ext import ThetaSketchAggregator
    configs = [
        ("timeseries count+longSum", TimeseriesQuery.of(
            "bench", [iv], [CountAggregator("n"),
                            LongSumAggregator("s", "metLong")],
            granularity="hour")),
        ("topN doubleSum+selector", TopNQuery.of(
            "bench", [iv], "dimB", "ds", 100,
            [DoubleSumAggregator("ds", "metFloat")],
            granularity="all", filter=SelectorFilter("dimA", sel))),
        ("hll cardinality", TimeseriesQuery.of(
            "bench", [iv], [HyperUniqueAggregator("u", "dimB")],
            granularity="all")),
        ("theta sketch", TimeseriesQuery.of(
            "bench", [iv], [ThetaSketchAggregator("u", "dimB")],
            granularity="all")),
    ]
    ex = QueryExecutor(segs)
    ok = True
    for name, q in configs:
        try:
            t0 = time.time()
            ex.run(q)
            warm = time.time() - t0
            ts = []
            for _ in range(3):
                t0 = time.time()
                ex.run(q)
                ts.append(time.time() - t0)
            log(f"[extended] {name}: {min(ts) * 1e3:.0f}ms "
                f"({rows / min(ts) / 1e6:.0f}M rows/s, warm {warm:.1f}s)")
        except Exception as e:
            log(f"[extended] {name}: FAILED {type(e).__name__}: "
                f"{str(e)[:120]}")
            ok = False
    return ok


def stage_bench() -> bool:
    t0 = time.time()
    p = subprocess.run([sys.executable, "bench.py"], cwd=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), env=dict(os.environ),
        capture_output=True, text=True, timeout=3600)
    log(f"[bench] rc={p.returncode} ({time.time() - t0:.0f}s)")
    for line in p.stderr.splitlines()[-6:]:
        log(f"[bench]   {line}")
    if p.returncode != 0:
        return False
    try:
        out = json.loads(p.stdout.strip().splitlines()[-1])
        value = float(out["value"])
    except (IndexError, ValueError, KeyError, TypeError) as e:
        log(f"[bench] UNPARSEABLE output ({e}): {p.stdout[-200:]!r}")
        return False
    log(f"[bench] {out}")
    floor = 49_054_911          # BENCH_r03 — never regress below this
    if value < floor:
        log(f"[bench] REGRESSION: {value:,.0f} < {floor:,}")
        return False
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=12_500_000)
    ap.add_argument("--skip-bench", action="store_true")
    args = ap.parse_args()
    for name, fn in [("sanity", stage_sanity),
                     ("pallas", lambda: stage_pallas(args.rows)),
                     ("strategies", lambda: stage_strategies(args.rows)),
                     ("extended", lambda: stage_extended(args.rows)),
                     ("bench", None if args.skip_bench else stage_bench)]:
        if fn is None:
            log(f"[{name}] skipped")
            continue
        if not fn():
            log(f"FAILED at stage {name}")
            return 1
    log("ALL STAGES PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
